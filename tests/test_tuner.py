"""Closed-loop autotuner tests (ISSUE 15).

Controller core (utils/tuner.py) under a deterministic synthetic-
signal harness: hill-climb convergence, hysteresis (no oscillation on
a noisy plateau), guarded rollback + direction blacklist on injected
regression, guard-signal veto, pinning.  Plus the live-mutation seams:
every runtime-tunable batcher knob is flipped on a LIVE EncodeBatcher
mid-stream and the output must stay bit-exact with the synchronous
ecutil.encode path, and StagingPool depth retargets without touching
in-flight slots.
"""
import os
import threading

import pytest

from ceph_tpu.utils.config import Config
from ceph_tpu.utils.tuner import (VERDICT_KEPT, VERDICT_NEUTRAL,
                                  VERDICT_PROBE, VERDICT_ROLLED_BACK,
                                  KnobSpec, Tuner, knobs_from_config)


def make_knob(name="k", lo=1, hi=64, init=2, is_int=True, **kw):
    """A KnobSpec over a plain cell, returned with the cell so tests
    can read/poke the 'live' value."""
    cell = {"v": init}
    spec = KnobSpec(name, lo, hi, is_int,
                    get=lambda: cell["v"],
                    set=lambda v: cell.__setitem__("v", v), **kw)
    return spec, cell


def drive(tuner, objective_of, n):
    """n controller ticks; objective is a pure function of the live
    knob values at tick time (the deterministic synthetic plant)."""
    records = []
    for _ in range(n):
        rec = tuner.step(objective_of())
        if rec is not None:
            records.append(rec)
    return records


# -- control law ------------------------------------------------------

def test_hill_climb_converges_to_optimum():
    """Throughput rises with the knob up to 8 then falls: the
    controller must climb 2 -> 8 and hold there (kept on the way up,
    rollbacks past the peak, neutral/quiet at the plateau)."""
    spec, cell = make_knob(init=2)
    t = Tuner("t", [spec], hysteresis=0.02, cooldown_ticks=0,
              blacklist_ticks=4)

    def objective():
        v = cell["v"]
        return 100.0 * min(v, 8) - 60.0 * max(0, v - 8)

    recs = drive(t, objective, 60)
    assert cell["v"] == 8, f"expected convergence to 8, at {cell['v']}"
    assert t.counts[VERDICT_KEPT] >= 3          # climbed, not jumped
    assert t.counts[VERDICT_ROLLED_BACK] >= 1   # found the cliff
    verdicts = {r["verdict"] for r in recs}
    assert VERDICT_PROBE in verdicts
    # bounds were never violated at any point of the walk
    assert all(spec.lo <= r["new"] <= spec.hi for r in recs)


def test_noisy_plateau_does_not_oscillate():
    """Objective noise inside the hysteresis deadband must read as
    neutral: no kept, no rollback/blacklist, knob restored after every
    probe -- i.e. the controller doesn't random-walk a flat system."""
    spec, cell = make_knob(init=8)
    t = Tuner("t", [spec], hysteresis=0.05, cooldown_ticks=0,
              blacklist_ticks=4)
    noise = [0.0, +0.02, -0.02, +0.01, -0.015, +0.005]
    i = [0]

    def objective():
        i[0] += 1
        return 1000.0 * (1.0 + noise[i[0] % len(noise)])

    drive(t, objective, 40)
    assert cell["v"] == 8, "plateau walk moved the knob"
    assert t.counts[VERDICT_KEPT] == 0
    assert t.counts[VERDICT_ROLLED_BACK] == 0
    assert t.counts[VERDICT_NEUTRAL] == t.counts[VERDICT_PROBE] > 0
    assert t.dump()["blacklist"] == []


def test_injected_regression_rolls_back_and_blacklists():
    """Any move off 8 tanks the objective: both directions must be
    probed at most once, rolled back (value restored), blacklisted,
    and the controller then holds still until the blacklist expires."""
    spec, cell = make_knob(init=8)
    t = Tuner("t", [spec], hysteresis=0.02, cooldown_ticks=0,
              blacklist_ticks=100)

    def objective():
        return 800.0 if cell["v"] == 8 else 100.0

    drive(t, objective, 30)
    assert cell["v"] == 8, "regressing probe was not rolled back"
    assert t.counts[VERDICT_ROLLED_BACK] == 2   # once per direction
    assert t.counts[VERDICT_KEPT] == 0
    d = t.dump()
    assert {(b["knob"], b["dir"]) for b in d["blacklist"]} == \
        {("k", +1), ("k", -1)}
    # fully blacklisted: no further probes happen
    assert t.counts[VERDICT_PROBE] == 2


def test_blacklist_expires_and_reprobes():
    spec, cell = make_knob(init=8)
    t = Tuner("t", [spec], hysteresis=0.02, cooldown_ticks=0,
              blacklist_ticks=3)

    def objective():
        return 800.0 if cell["v"] == 8 else 100.0

    drive(t, objective, 12)
    assert t.counts[VERDICT_PROBE] > 2, \
        "blacklist never expired -> knob never re-probed"
    assert cell["v"] == 8                        # still guarded


def test_guard_trip_forces_rollback():
    """A probe that improves the objective but trips a guard signal
    (SLO burn, overlap collapse) must still be reverted + counted."""
    spec, cell = make_knob(init=4)
    t = Tuner("t", [spec], hysteresis=0.02, cooldown_ticks=0)
    rec = t.step(100.0)                          # probe applied
    assert rec["verdict"] == VERDICT_PROBE
    assert cell["v"] != 4
    rec = t.step(500.0, guard="slo_burn:client") # better, but tripped
    assert rec["verdict"] == VERDICT_ROLLED_BACK
    assert rec["guard"] == "slo_burn:client"
    assert cell["v"] == 4
    assert t.counts["guard_trips"] == 1
    # and a standing guard stops NEW probes from starting at all
    assert t.step(500.0, guard="overlap_collapse") is None


def test_idle_system_is_left_alone():
    """objective <= 0 (no traffic) must never start a probe."""
    spec, cell = make_knob(init=4)
    t = Tuner("t", [spec], cooldown_ticks=0)
    for _ in range(10):
        assert t.step(0.0) is None
    assert cell["v"] == 4
    assert t.counts[VERDICT_PROBE] == 0


def test_pinned_knob_is_never_touched():
    pinned, pcell = make_knob(name="p", init=4, pinned=True)
    free, fcell = make_knob(name="f", init=4)
    t = Tuner("t", [pinned, free], hysteresis=0.02, cooldown_ticks=0)
    drive(t, lambda: 100.0 + fcell["v"], 20)
    assert pcell["v"] == 4, "pinned knob moved"
    assert t.counts[VERDICT_PROBE] > 0           # free knob still walked


def test_cooldown_spaces_decisions():
    spec, cell = make_knob(init=4)
    t = Tuner("t", [spec], hysteresis=0.02, cooldown_ticks=2)
    assert t.step(100.0)["verdict"] == VERDICT_PROBE
    assert t.step(100.0) is None                 # settling
    assert t.step(100.0) is None
    assert t.step(200.0)["verdict"] == VERDICT_KEPT


def test_zero_auto_knob_seeds_up_and_never_goes_negative():
    spec, cell = make_knob(init=0, lo=0, hi=100, seed=20)
    t = Tuner("t", [spec], hysteresis=0.02, cooldown_ticks=0)
    rec = t.step(100.0)
    assert rec["verdict"] == VERDICT_PROBE and rec["new"] == 20
    t.step(10.0)                                 # regress: roll back
    assert cell["v"] == 0
    # down from 0 is unproposable; up is blacklisted -> hold
    assert t.step(100.0) is None


def test_dump_shape_and_audit_ring():
    spec, cell = make_knob(init=4)
    t = Tuner("osd.0", [spec], cooldown_ticks=0)
    t.step(100.0)
    t.step(200.0)
    d = t.dump()
    assert d["name"] == "osd.0"
    assert d["knobs"][0]["name"] == "k"
    assert d["knobs"][0]["min"] == 1 and d["knobs"][0]["max"] == 64
    assert d["counts"][VERDICT_PROBE] == 1
    assert len(d["steps"]) == 2
    assert d["steps"][0]["verdict"] == VERDICT_PROBE
    assert d["steps"][1]["verdict"] == VERDICT_KEPT


# -- knob universe from the Option schema -----------------------------

def test_every_tunable_option_has_finite_bounds():
    """Satellite 1's audit, as a standing invariant: an Option marked
    tunable without finite min/max is a schema bug the controller
    would otherwise walk off a cliff."""
    conf = Config()
    tunables = conf.tunables()
    assert len(tunables) >= 4
    for opt in tunables:
        assert opt.min is not None and opt.max is not None, \
            f"tunable option {opt.name} lacks finite min/max bounds"
        assert opt.min < opt.max, opt.name
    names = {o.name for o in tunables}
    assert {"ec_tpu_queue_window_max_us", "ec_tpu_inflight_groups",
            "ec_tpu_staging_depth",
            "osd_ec_pipeline_segment_bytes"} <= names
    # QoS triples for the mgr half; peering deliberately NOT tunable
    assert "osd_mclock_scheduler_recovery_wgt" in names
    assert "osd_mclock_scheduler_peering_wgt" not in names


def test_knobs_from_config_live_set_and_pinning():
    conf = Config()
    knobs = knobs_from_config(
        conf,
        {"ec_tpu_inflight_groups": {},
         "ec_tpu_staging_depth": {},
         "ec_tpu_queue_window_max_us": {"seed": 20000}},
        pinned="ec_tpu_staging_depth, ec_tpu_queue_window_max_us")
    by = {k.name: k for k in knobs}
    assert len(by) == 3
    assert by["ec_tpu_staging_depth"].pinned
    assert by["ec_tpu_queue_window_max_us"].pinned
    assert by["ec_tpu_queue_window_max_us"].seed == 20000
    infl = by["ec_tpu_inflight_groups"]
    assert not infl.pinned and infl.is_int
    old = infl.get()
    infl.set(old + 1)                    # through Config.set(runtime)
    assert conf["ec_tpu_inflight_groups"] == old + 1
    # Option bounds arrived in the spec: the controller's clamp range
    assert infl.lo >= 1 and infl.hi <= 64


def test_knobs_from_config_skips_unbounded_tunable():
    """Defense in depth: even if a schema slips an unbounded tunable
    in, knobs_from_config refuses to walk it."""
    conf = Config()
    with conf._lock:
        opt = conf.schema["ec_tpu_inflight_groups"]
    import dataclasses
    bad = dataclasses.replace(opt, max=None)
    try:
        with conf._lock:
            conf.schema["ec_tpu_inflight_groups"] = bad
        knobs = knobs_from_config(conf,
                                  {"ec_tpu_inflight_groups": {}})
        assert knobs == []
    finally:
        with conf._lock:
            conf.schema["ec_tpu_inflight_groups"] = opt


# -- live-mutation seams (satellite 2): bit-exact mid-stream ----------

def test_live_knob_mutation_keeps_output_bit_exact():
    """Flip every runtime-tunable batcher knob on a LIVE batcher in
    the middle of an encode stream; every op's chunk map must stay
    bit-identical to the synchronous ecutil.encode path."""
    from ceph_tpu.ec import registry as ecreg
    from ceph_tpu.osd import ecutil
    from ceph_tpu.osd.batcher import EncodeBatcher

    conf = {"ec_tpu_batch_stripes": 1024,
            "ec_tpu_queue_window_us": 2_000,
            "ec_tpu_queue_window_max_us": 30_000,
            "ec_tpu_inflight_groups": 4,
            "ec_tpu_staging_depth": 2}
    EncodeBatcher.reset_learning()
    codec = ecreg.instance().factory(
        "tpu", {"k": "2", "m": "1", "technique": "reed_sol_van"})
    b = EncodeBatcher(conf)
    try:
        sinfo = ecutil.StripeInfo(2, 8192)
        n_ops = 24
        datas = [os.urandom((1 + i % 3) * 8192) for i in range(n_ops)]
        got = {}
        done = threading.Event()
        lock = threading.Lock()

        def cb(i):
            def _cb(chunks):
                with lock:
                    got[i] = chunks
                    if len(got) == n_ops:
                        done.set()
            return _cb

        # mutation schedule: hit each knob mid-stream, twice (up and
        # down) so both resize directions run against live traffic
        mutations = {
            6: ("ec_tpu_inflight_groups", 1),
            10: ("ec_tpu_queue_window_max_us", 500),
            14: ("ec_tpu_staging_depth", 8),
            18: ("ec_tpu_inflight_groups", 16),
            20: ("ec_tpu_queue_window_max_us", 100_000),
            22: ("ec_tpu_staging_depth", 1),
        }
        for i, data in enumerate(datas):
            if i in mutations:
                key, val = mutations[i]
                conf[key] = val          # a runtime conf.set
            b.submit(codec, sinfo, data, cb(i))
        assert done.wait(60), f"stream stalled: {len(got)}/{n_ops}"
        for i, data in enumerate(datas):
            assert got[i] == ecutil.encode(sinfo, codec, data), \
                f"op {i} chunks diverged after live knob mutation"
        # the seams actually latched the final values
        b.apply_tuning()
        assert b.inflight_groups == 16
        assert b._completions.maxsize == 16
        assert b.window_max_s == pytest.approx(0.1)
    finally:
        b.stop()


def test_staging_pool_set_depth_live():
    """Raising depth admits new slots; lowering stops growth without
    touching slots already in flight (bit-exactness by construction:
    buffers are never resized or freed under a writer)."""
    from ceph_tpu.ops.jax_engine import StagingPool
    pool = StagingPool(depth=2)
    shape = (1, 2, 512)
    a = pool.acquire(shape)
    bslot = pool.acquire(shape)
    assert pool.allocs == 2
    pool.set_depth(4)
    c = pool.acquire(shape)              # third slot now admitted
    assert pool.allocs == 3
    pool.set_depth(1)                    # shrink target below live
    host_a = a.host
    pool.release(shape, a, None)
    got = pool.acquire(shape)            # in-flight slot keeps cycling
    assert got.host is host_a
    assert pool.allocs == 3              # no growth past the target
    pool.release(shape, bslot, None)
    pool.release(shape, c, None)
    pool.release(shape, got, None)


def test_queue_window_zero_means_auto_restores_adaptive_ceiling():
    from ceph_tpu.osd.batcher import EncodeBatcher
    conf = {"ec_tpu_batch_stripes": 64,
            "ec_tpu_queue_window_us": 1_000,
            "ec_tpu_queue_window_max_us": 50_000}
    EncodeBatcher.reset_learning()
    b = EncodeBatcher(conf)
    try:
        b.apply_tuning()
        assert b.window_max_s == pytest.approx(0.05)
        conf["ec_tpu_queue_window_max_us"] = 0
        b.apply_tuning()
        # 0 = auto: back to the adaptive default ceiling
        assert b.window_max_s == pytest.approx(
            max(b.window_base_s * 16, 0.02))
        assert b.dyn_window_s <= b.window_max_s
    finally:
        b.stop()
