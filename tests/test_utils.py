"""Runtime-primitive tests: config layering/observers, perf counters,
admin socket round trip, op tracker (reference analogs:
src/test/common/test_config.cc, perf_counters tests,
test_admin_socket.cc)."""
import os
import tempfile
import threading

import pytest

from ceph_tpu.utils import (AdminSocket, Config, OpTracker, PerfCounters,
                            PerfCountersCollection, TimeScope,
                            admin_command)


class TestConfig:
    def test_defaults(self):
        conf = Config()
        assert conf.get("osd_op_num_shards") == 5
        assert conf["ms_crc_data"] is True

    def test_unknown_key(self):
        conf = Config()
        with pytest.raises(KeyError):
            conf.get("no_such_option")
        with pytest.raises(KeyError):
            conf.set("no_such_option", 1)

    def test_precedence(self):
        conf = Config()
        conf.set("osd_op_num_shards", 7, source="file")
        assert conf.get("osd_op_num_shards") == 7
        conf.set("osd_op_num_shards", 9, source="runtime")
        assert conf.get("osd_op_num_shards") == 9
        # lower-precedence source does not override
        conf.set("osd_op_num_shards", 3, source="file")
        assert conf.get("osd_op_num_shards") == 9

    def test_validation(self):
        conf = Config()
        with pytest.raises(ValueError):
            conf.set("osd_op_num_shards", 0)      # min=1
        with pytest.raises(ValueError):
            conf.set("osd_op_num_shards", "abc")
        conf.set("ms_crc_data", "false")
        assert conf.get("ms_crc_data") is False

    def test_observer(self):
        conf = Config()
        seen = []
        conf.add_observer("osd_recovery_max_active",
                          lambda k, v: seen.append((k, v)))
        conf.set("osd_recovery_max_active", 8)
        conf.set("osd_recovery_max_active", 8)  # no-op: unchanged
        assert seen == [("osd_recovery_max_active", 8)]

    def test_env_source(self, monkeypatch):
        monkeypatch.setenv("CEPH_TPU_OSD_MAX_BACKFILLS", "5")
        conf = Config()
        assert conf.get("osd_max_backfills") == 5

    def test_diff(self):
        conf = Config()
        conf.set("osd_max_backfills", 4)
        assert conf.diff() == {"osd_max_backfills": 4}


class TestPerfCounters:
    def test_counter_and_avg(self):
        c = PerfCounters("osd")
        c.add("ops")
        c.add_time_avg("op_lat")
        for i in range(10):
            c.inc("ops")
            c.tinc("op_lat", 0.5)
        assert c.get("ops") == 10
        assert c.avg("op_lat") == pytest.approx(0.5)
        dump = c.dump()
        assert dump["ops"] == 10
        assert dump["op_lat"] == {"avgcount": 10, "sum": pytest.approx(5.0)}

    def test_histogram(self):
        c = PerfCounters("osd")
        c.add_histogram("sizes", [10, 100, 1000])
        for v in (5, 50, 500, 5000, 7):
            c.hinc("sizes", v)
        assert c.dump()["sizes"]["buckets"] == [2, 1, 1, 1]

    def test_collection(self):
        coll = PerfCountersCollection()
        a = coll.create("osd")
        a.add("ops")
        a.inc("ops", 3)
        assert coll.perf_dump()["osd"]["ops"] == 3

    def test_time_scope(self):
        c = PerfCounters("x")
        c.add_time_avg("lat")
        with TimeScope(c, "lat"):
            pass
        assert c.dump()["lat"]["avgcount"] == 1


class TestAdminSocket:
    def test_round_trip(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "asok")
            sock = AdminSocket(path)
            coll = PerfCountersCollection()
            pc = coll.create("osd")
            pc.add("ops")
            pc.inc("ops", 42)
            sock.register("perf dump", lambda cmd: coll.perf_dump())
            sock.register("echo", lambda cmd: cmd.get("payload"))
            sock.start()
            try:
                out = admin_command(path, "perf dump")
                assert out["osd"]["ops"] == 42
                assert admin_command(path, "echo", payload=[1, 2]) == [1, 2]
                with pytest.raises(RuntimeError, match="unknown command"):
                    admin_command(path, "nope")
                assert "perf dump" in admin_command(path, "help")
            finally:
                sock.stop()


class TestOpTracker:
    def test_lifecycle(self):
        t = OpTracker(history_size=2)
        op = t.create("osd_op(write)")
        op.mark_event("queued")
        op.mark_event("commit")
        assert len(t.dump_ops_in_flight()) == 1
        op.finish()
        assert t.dump_ops_in_flight() == []
        hist = t.dump_historic_ops()
        assert len(hist) == 1
        events = [e["event"] for e in hist[0]["events"]]
        assert events == ["initiated", "queued", "commit", "done"]

    def test_slow_ops(self):
        t = OpTracker(slow_op_warn_threshold=0.0)
        t.create("slowpoke")
        assert len(t.slow_ops()) == 1
