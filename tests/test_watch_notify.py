"""watch/notify tests.

Reference analog: src/test/librados/watch_notify.cc — registration,
notify fan-out + ack gathering, timeouts, unwatch, and watch survival
across primary failover (the lingering-op machinery RBD/RGW
coordination relies on)."""
import threading
import time

import pytest

from ceph_tpu.client.rados import RadosError
from ceph_tpu.cluster import Cluster


@pytest.fixture(scope="module")
def cl():
    with Cluster(n_osds=3) as c:
        for i in range(3):
            c.wait_for_osd_up(i, 20)
        c.create_pool("wn", "replicated", size=2)
        yield c


def test_watch_notify_roundtrip(cl):
    r1 = cl.rados()
    r2 = cl.rados()
    io1 = r1.open_ioctx("wn")
    io2 = r2.open_ioctx("wn")
    io1.write_full("obj", b"x")

    got1, got2 = [], []
    ev1, ev2 = threading.Event(), threading.Event()
    c1 = io1.watch("obj", lambda who, pl: (got1.append((who, pl)),
                                           ev1.set()))
    c2 = io2.watch("obj", lambda who, pl: (got2.append((who, pl)),
                                           ev2.set()))
    assert len(io1.list_watchers("obj")) == 2

    r3 = cl.rados()
    io3 = r3.open_ioctx("wn")
    out = io3.notify("obj", b"hello", timeout_ms=10_000)
    assert ev1.wait(5) and ev2.wait(5)
    assert got1[0][1] == b"hello" and got2[0][1] == b"hello"
    assert got1[0][0].startswith("client.")   # notifier name
    assert len(out["acks"]) == 2 and not out["timed_out"]

    # unwatch: only the remaining watcher acks
    io2.unwatch("obj", c2)
    out = io3.notify("obj", b"again", timeout_ms=10_000)
    assert len(out["acks"]) == 1 and not out["timed_out"]
    io1.unwatch("obj", c1)
    assert io1.list_watchers("obj") == []


def test_notify_timeout_on_slow_watcher(cl):
    r1 = cl.rados()
    io1 = r1.open_ioctx("wn")
    io1.write_full("slow", b"x")
    cookie = io1.watch("slow", lambda who, pl: time.sleep(8))
    r2 = cl.rados()
    io2 = r2.open_ioctx("wn")
    t0 = time.monotonic()
    out = io2.notify("slow", b"p", timeout_ms=1500)
    took = time.monotonic() - t0
    assert out["timed_out"], "slow watcher should time the notify out"
    assert took < 6, "notify must return at the timeout, not at ack"
    io1.unwatch("slow", cookie)


def test_watch_requires_object(cl):
    io = cl.rados().open_ioctx("wn")
    with pytest.raises(RadosError):
        io.watch("missing-obj", lambda who, pl: None)


def test_two_watches_one_client_both_must_ack(cl):
    """A client with TWO watches on one object: the notify completes
    only after BOTH ack (pending is keyed by (client, cookie))."""
    r1 = cl.rados()
    io1 = r1.open_ioctx("wn")
    io1.write_full("dbl", b"x")
    seen = []
    c1 = io1.watch("dbl", lambda who, pl: seen.append(1))
    c2 = io1.watch("dbl", lambda who, pl: (time.sleep(1.0),
                                           seen.append(2)))
    out = cl.rados().open_ioctx("wn").notify("dbl", b"p",
                                             timeout_ms=10_000)
    assert len(out["acks"]) == 2 and not out["timed_out"]
    assert sorted(seen) == [1, 2]
    io1.unwatch("dbl", c1)
    io1.unwatch("dbl", c2)


def test_watch_survives_replica_death_same_primary(cl):
    """An interval change that KEEPS the primary (a replica dies)
    still wipes the PG's volatile watcher registry — the lingering
    registration must re-register anyway."""
    r1 = cl.rados()
    io1 = r1.open_ioctx("wn")
    io1.write_full("rd", b"x")
    ev = threading.Event()
    io1.watch("rd", lambda who, pl: ev.set())
    osdmap = r1.objecter.osdmap
    pgid = osdmap.object_locator_to_pg("rd", io1.pool_id)
    _, _, acting, primary = osdmap.pg_to_up_acting_osds(pgid)
    replica = next(o for o in acting if o is not None and o != primary)
    cl.kill_osd(replica)
    cl.wait_for_osd_down(replica)
    io2 = cl.rados().open_ioctx("wn")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if io2.list_watchers("rd"):
            break
        time.sleep(0.3)
    assert io2.list_watchers("rd"), \
        "watch lost across a same-primary interval change"
    out = io2.notify("rd", b"still-there", timeout_ms=10_000)
    assert ev.wait(10) and len(out["acks"]) == 1
    cl.revive_osd(replica)
    cl.wait_for_osd_up(replica)


def test_aio_write_carries_snap_context(cl):
    """aio_write_full must trigger snapshot COW exactly like the
    synchronous path."""
    io = cl.rados().open_ioctx("wn")
    io.write_full("aiosnap", b"v1" * 100)
    s1 = io.selfmanaged_snap_create()
    io.set_snap_context(s1, [s1])
    comp = io.aio_write_full("aiosnap", b"v2" * 100)
    assert comp.wait(10) == 0
    io.snap_set_read(s1)
    assert io.read("aiosnap") == b"v1" * 100
    io.snap_set_read(0)
    assert io.read("aiosnap") == b"v2" * 100


def test_watch_survives_primary_failover(cl):
    r1 = cl.rados()
    io1 = r1.open_ioctx("wn")
    io1.write_full("fo", b"x")
    hits = []
    ev = threading.Event()
    io1.watch("fo", lambda who, pl: (hits.append(pl), ev.set()))

    # find and kill the primary of fo's PG
    osdmap = r1.objecter.osdmap
    pgid = osdmap.object_locator_to_pg("fo", io1.pool_id)
    _, _, _, primary = osdmap.pg_to_up_acting_osds(pgid)
    cl.kill_osd(primary)
    cl.wait_for_osd_down(primary)

    # the lingering watch must re-register on the new primary
    r2 = cl.rados()
    io2 = r2.open_ioctx("wn")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if io2.list_watchers("fo"):
                break
        except RadosError:
            pass
        time.sleep(0.3)
    assert io2.list_watchers("fo"), "watch did not survive failover"
    out = io2.notify("fo", b"after-failover", timeout_ms=10_000)
    assert ev.wait(10)
    assert hits[0] == b"after-failover"
    assert len(out["acks"]) == 1
    cl.revive_osd(primary)
    cl.wait_for_osd_up(primary)
