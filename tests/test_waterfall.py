"""Cluster-path waterfall (ISSUE 7): hop-ledger wire compat across
mixed versions, the interval-charging invariant, lock/queue contention
telemetry, the sampling profiler, and the end-to-end waterfall on a
live cluster.

The wire-compat contract under test: the ledger is a TRAILING payload
field, so a pre-ledger peer's bytes decode with ``hops=None`` (never an
error), and a pre-ledger decoder reading a ledger-bearing payload sees
every original field untouched — both directions, classic messenger
and crimson.
"""
import threading
import time

import pytest

from ceph_tpu.cluster import Cluster
from ceph_tpu.cluster import test_config as make_conf
from ceph_tpu.msg import messages as M
from ceph_tpu.msg.message import (HEADER_LEN, decode_frame_body,
                                  decode_frame_header, encode_frame)
from ceph_tpu.msg.messenger import Dispatcher
from ceph_tpu.utils.encoding import Decoder, Encoder
from ceph_tpu.utils.hops import (CHARGE_ORDER, CONDITIONAL_HOPS,
                                 HOP_BOUNDS, HOP_ORDER, HopAccum,
                                 charge, decode_ledger, encode_ledger,
                                 merge_dumps, waterfall_block)


def _carriers():
    """One instance of every ledger-bearing message type."""
    return [
        M.MOSDOp(client="client.7", tid=3, epoch=9, pool=1, oid="obj",
                 ops=[M.OSDOp("write", 0, 5, b"hello")],
                 pgid_seed=2, flags=1),
        M.MOSDOpReply(tid=3, result=0, epoch=9, out_data=[b"x"],
                      extra={"v": 1}),
        M.MOSDECSubOpWrite(pgid="1.2", shard=3, from_osd=0, tid=8,
                           epoch=4, txn=b"\x01\x02", log_entries=[],
                           at_version=(4, 17)),
        M.MOSDECSubOpWriteReply(pgid="1.2", shard=3, from_osd=2, tid=8,
                                epoch=4, committed=True, result=0),
        M.MOSDRepOp(pgid="2.0", from_osd=1, tid=5, epoch=3, txn=b"tx",
                    log_entries=[], at_version=(3, 2)),
        M.MOSDRepOpReply(pgid="2.0", from_osd=2, tid=5, epoch=3,
                         result=0),
        M.MOSDECSubOpRead(pgid="1.2", shard=1, from_osd=0, tid=9,
                          epoch=4, reads=[("obj", 0, 4096)],
                          attrs_to_read=["_"], for_recovery=True),
        M.MOSDECSubOpReadReply(pgid="1.2", shard=1, from_osd=3, tid=9,
                               epoch=4, buffers=[("obj", 0, b"d")],
                               attrs=[("obj", {"_": b"v"})],
                               errors=[("gone", -2)]),
        M.MOSDPGPush(pgid="1.2", shard=2, from_osd=0, epoch=4),
        M.MOSDPGPull(pgid="1.2", shard=2, from_osd=1, epoch=4,
                     oids=["obj"]),
        M.MOSDPGPushReply(pgid="1.2", shard=2, from_osd=2, epoch=4,
                          oids=["obj"]),
    ]


def _stamp(msg, names, t0=1000.0):
    for i, name in enumerate(names):
        msg.stamp_hop(name, _now=lambda t=t0 + i / 100.0: t)
    return msg


# ------------------------------------------------------------- codec
@pytest.mark.parametrize("msg", _carriers(),
                         ids=lambda m: m.get_type_name())
def test_ledger_rides_every_carrier(msg):
    _stamp(msg, ("client_send", "recv", "store_apply", "commit_sent"))
    out = type(msg).decode_payload(msg.encode_payload())
    assert out.hops == msg.hops


@pytest.mark.parametrize("msg", _carriers(),
                         ids=lambda m: m.get_type_name())
def test_old_peer_payload_decodes_with_no_ledger(msg):
    """Direction old->new: a pre-ledger sender's payload is exactly
    today's payload minus the trailing ledger field.  It must decode
    to the same message with hops defaulted to None — never raise."""
    _stamp(msg, ("client_send", "recv"))
    new_payload = msg.encode_payload()
    e = Encoder()
    encode_ledger(e, msg.hops)
    tail = len(e.build())
    assert tail == 1 + 9 * len(msg.hops)
    old_payload = new_payload[:-tail]
    out = type(msg).decode_payload(old_payload)
    assert out.hops is None
    # the non-ledger fields survived the truncation untouched
    ref = type(msg).decode_payload(new_payload)
    ref.hops = None
    assert out.encode_payload() == ref.encode_payload()


def test_new_payload_readable_by_old_decoder():
    """Direction new->old: a pre-ledger decoder reads the prefix
    fields and never looks at the trailing ledger.  Replayed here
    verbatim from the pre-ledger decode_payload of
    MOSDECSubOpWriteReply and MOSDRepOpReply."""
    m = _stamp(M.MOSDECSubOpWriteReply(pgid="1.2", shard=3, from_osd=2,
                                       tid=8, epoch=4, committed=True,
                                       result=-5, seg=2),
               ("recv", "store_apply", "commit_sent"))
    d = Decoder(m.encode_payload())
    assert (d.str(), d.i32(), d.i32(), d.u64(), d.u32(), d.bool(),
            d.i32(), d.u32()) == ("1.2", 3, 2, 8, 4, True, -5, 2)
    assert d.remaining() == 1 + 9 * 3      # old decoder ignores this

    r = _stamp(M.MOSDRepOpReply(pgid="2.0", from_osd=1, tid=5, epoch=3,
                                result=0), ("recv",))
    d = Decoder(r.encode_payload())
    assert (d.str(), d.i32(), d.u64(), d.u32(), d.i32()) == \
        ("2.0", 1, 5, 3, 0)
    assert d.remaining() == 1 + 9


def test_decoder_skips_unknown_hop_ids():
    """A NEWER peer may define hops we do not know; their entries are
    skipped, ours kept."""
    e = Encoder()
    e.u8(2)
    e.u8(0)
    e.f64(1000.0)
    e.u8(200)                               # from the future
    e.f64(1001.0)
    hops = decode_ledger(Decoder(e.build()))
    assert hops == {"client_send": 1000.0}


def test_garbled_ledger_trailer_reads_as_none():
    e = Encoder()
    e.u8(5)                                 # claims 5 entries, has 0
    assert decode_ledger(Decoder(e.build())) is None
    assert decode_ledger(Decoder(b"")) is None


def test_frame_roundtrip_keeps_ledger():
    msg = _stamp(_carriers()[0], ("client_send", "msgr_enqueue",
                                  "wire_sent"))
    msg.seq = 5
    frame = encode_frame(msg)
    mtype, seq, plen = decode_frame_header(frame[:HEADER_LEN])
    out = decode_frame_body(mtype, seq, frame[:HEADER_LEN],
                            frame[HEADER_LEN:HEADER_LEN + plen],
                            frame[HEADER_LEN + plen:])
    assert out.hops == msg.hops


# ----------------------------------------------------- charge invariant
def test_charge_sum_equals_wall_with_gaps():
    """The exactness invariant: charged intervals sum to last-first
    even when the path skips hops (sub-ops never see pg_queued)."""
    hops = {"client_send": 10.0, "msgr_enqueue": 10.002,
            "wire_sent": 10.003, "recv": 10.010,
            "dispatch_queued": 10.011, "pg_locked": 10.020,
            "store_apply": 10.090, "commit_sent": 10.091,
            "client_complete": 10.100}
    charged = charge(hops)
    assert abs(sum(dt for _, dt in charged) - 0.100) < 1e-12
    names = [n for n, _ in charged]
    assert "client_send" not in names       # first hop ends no interval
    assert "pg_queued" not in names         # absent hop charges nothing
    # the skipped hop's time folded into the NEXT present hop
    assert dict(charged)["pg_locked"] == pytest.approx(0.009)


def test_stamp_hop_first_wins():
    """Replies carry the request's ledger; the generic messenger
    stamps on the reply leg must not clobber the request-leg stamps."""
    m = M.MOSDOpReply(tid=1)
    m.stamp_hop("recv", _now=lambda: 5.0)
    m.stamp_hop("recv", _now=lambda: 9.0)
    assert m.hops == {"recv": 5.0}


def test_hop_accum_and_waterfall_block():
    acc = HopAccum()
    for _ in range(4):
        acc.observe_wire({"client_send": 0.0, "recv": 0.010,
                          "store_apply": 0.030,
                          "client_complete": 0.040})
    acc.observe_wire(None)                  # old peer: ignored
    acc.observe_wire({"recv": 1.0})         # single stamp: ignored
    d = acc.dump()
    assert d["ops"] == 4
    assert d["op_seconds"] == pytest.approx(4 * 0.040)
    wf = waterfall_block(d, wall_s=0.32)
    assert wf["sum_of_shares"] == pytest.approx(1.0, abs=1e-3)
    assert wf["vs_wall"] == pytest.approx(1.0, abs=1e-3)
    assert sum(wf["scaled_s"].values()) == pytest.approx(0.32, rel=1e-3)
    assert wf["top_hop"] == "store_apply"
    assert set(wf["p99_s"]) == {"recv", "store_apply",
                                "client_complete"}


def test_merge_dumps_adds_buckets_and_recomputes_percentiles():
    a, b = HopAccum(), HopAccum()
    a.observe_wire({"client_send": 0.0, "recv": 0.001})
    b.observe_wire({"client_send": 0.0, "recv": 0.200})
    merged = merge_dumps([a.dump(), b.dump(), {}])
    assert merged["ops"] == 2
    assert merged["hop_counts"]["recv"] == 2
    assert sum(merged["buckets"]["recv"]) == 2
    assert merged["p99_s"]["recv"] >= 0.200 * 0.9
    assert len(merged["bounds"]) == len(HOP_BOUNDS)


# --------------------------------------------- live wire, both stacks
class _Sink(Dispatcher):
    def __init__(self):
        self.got = []
        self.cond = threading.Condition()

    def ms_dispatch(self, conn, msg):
        with self.cond:
            self.got.append(msg)
            self.cond.notify_all()
        return True

    def ms_handle_reset(self, conn):
        pass

    def wait_n(self, n, timeout=10.0):
        deadline = time.monotonic() + timeout
        with self.cond:
            while len(self.got) < n:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self.cond.wait(left)
        return True


def _ledgerless(msg):
    """Make ``msg`` put a pre-ledger sender's bytes on the wire: its
    payload parts are frozen WITHOUT the trailing ledger field, however
    the messenger stamps it afterwards."""
    payload = msg.encode_payload()
    e = Encoder()
    encode_ledger(e, msg.hops)
    old = payload[:-len(e.build())]
    msg.encode_payload_parts = lambda: [old]
    return msg


def test_classic_wire_stamps_and_tolerates_old_sender():
    from ceph_tpu.msg.messenger import Messenger
    conf = make_conf()
    server = Messenger("osd.0", conf=conf)
    client = Messenger("client.1", conf=conf)
    sink = _Sink()
    server.add_dispatcher(sink)
    addr = server.bind(("127.0.0.1", 0))
    server.start()
    client.start()
    try:
        conn = client.connect_to(addr)
        # new sender -> new receiver: the wire stamps ride the ledger
        m = M.MOSDOp(client="client.1", tid=1, oid="o")
        m.stamp_hop("client_send")
        conn.send_message(m)
        # old (ledger-less) sender -> new receiver: decodes fine
        conn.send_message(_ledgerless(
            M.MOSDOp(client="client.1", tid=2, oid="o2")))
        assert sink.wait_n(2)
        new_m, old_m = sink.got
        hops = new_m.hops
        assert {"client_send", "msgr_enqueue", "wire_sent",
                "recv"} <= set(hops)
        assert hops["client_send"] <= hops["msgr_enqueue"] \
            <= hops["wire_sent"]
        assert old_m.oid == "o2"
        # only the local recv stamp — nothing came off the wire
        assert set(old_m.hops or {}) <= {"recv"}
    finally:
        client.shutdown()
        server.shutdown()


def test_crimson_wire_stamps_and_tolerates_old_sender():
    from ceph_tpu.crimson import Reactor
    from ceph_tpu.crimson.net import CrimsonMessenger
    conf = make_conf()
    ra, rb = Reactor(name="wf-ra"), Reactor(name="wf-rb")
    ra.start()
    rb.start()
    ma = CrimsonMessenger("osd.0", conf=conf, reactor=ra)
    mb = CrimsonMessenger("osd.1", conf=conf, reactor=rb)
    sink = _Sink()
    mb.add_dispatcher(sink)
    ma.add_dispatcher(_Sink())
    try:
        ma.bind()
        mb.bind()
        ma.start()
        mb.start()
        conn = ma.connect_to(mb.my_addr, peer_name="osd.1")
        m = M.MOSDECSubOpWrite(pgid="1.0", shard=1, from_osd=0, tid=1,
                               epoch=1, txn=b"t", log_entries=[],
                               at_version=(1, 1))
        m.stamp_hop("client_send")
        conn.send_message(m)
        conn.send_message(_ledgerless(M.MOSDECSubOpWrite(
            pgid="1.0", shard=1, from_osd=0, tid=2, epoch=1, txn=b"u",
            log_entries=[], at_version=(1, 2))))
        assert sink.wait_n(2)
        new_m, old_m = sink.got
        assert {"client_send", "msgr_enqueue", "wire_sent",
                "recv"} <= set(new_m.hops)
        assert old_m.tid == 2 and bytes(old_m.txn) == b"u"
        assert set(old_m.hops or {}) <= {"recv"}
    finally:
        ma.shutdown()
        mb.shutdown()
        ra.stop()
        rb.stop()


# ------------------------------------------------- live cluster waterfall
def _write_and_wall(c, pool, n=8, size=8192):
    import os
    io = c.rados(timeout=60).open_ioctx(pool)
    t0 = time.time()
    for i in range(n):
        io.write_full(f"wf{i}", os.urandom(size))
    return io, time.time() - t0


def _assert_waterfall(c, rad, wall, n):
    d = rad.objecter.hops.dump()
    assert d["ops"] >= n
    # the end-to-end MOSDOp WRITE path visits every hop after
    # client_send except the conditional ones (xshard_handoff only
    # appears on cross-shard handoffs; the read/decode/scrub hops
    # belong to the other op classes)
    assert set(d["hop_counts"]) >= \
        set(HOP_ORDER[1:]) - CONDITIONAL_HOPS
    # exactness: charged op-seconds are each op's own wall; serial
    # writes keep their sum within the measured client wall (slack for
    # time.time granularity and the final reply race)
    assert 0 < d["op_seconds"] <= wall * 1.25
    wf = waterfall_block(d, wall)
    assert abs(wf["sum_of_shares"] - 1.0) <= 0.05
    assert abs(wf["vs_wall"] - 1.0) <= 0.05
    assert wf["top_hop"] in HOP_ORDER
    # each OSD observed its sub-op round trips (no pg_queued leg there)
    sub = merge_dumps([o.hops.dump() for o in c.osds.values()
                       if o is not None])
    assert sub["ops"] > 0
    assert "pg_queued" not in sub["hop_counts"]
    assert {"recv", "store_apply", "commit_sent",
            "client_complete"} <= set(sub["hop_counts"])


def test_cluster_write_waterfall_invariant():
    """vstart EC write: the client-side waterfall covers every hop and
    its shares sum to the measured wall (the ISSUE 7 acceptance
    invariant, small-cluster tier-1 variant)."""
    with Cluster(n_osds=4, conf=make_conf()) as c:
        for i in range(4):
            c.wait_for_osd_up(i, 20)
        c.create_ec_profile("wf", plugin="tpu", k="2", m="1")
        c.create_pool("wfp", "erasure", erasure_code_profile="wf")
        rad = c.rados(timeout=60)
        io = rad.open_ioctx("wfp")
        import os
        t0 = time.time()
        for i in range(8):
            io.write_full(f"wf{i}", os.urandom(8192))
        wall = time.time() - t0
        _assert_waterfall(c, rad, wall, 8)
        # perf plumbing: hops + contention subsystems are live
        osd = next(o for o in c.osds.values() if o is not None)
        pd = osd.perf_coll.perf_dump()
        assert pd["hops"]["ops"] > 0
        assert "pg_lock_acquires" in pd["contention"]
        assert "batcher_cond_wait_us" in pd["contention"]
        assert pd["contention"]["msgr_sendq_depth_hwm"] >= 0


@pytest.mark.slow
def test_cluster_write_waterfall_invariant_k8m4():
    """The full bench shape: k=8 m=4 over 13 OSDs."""
    with Cluster(n_osds=13, conf=make_conf()) as c:
        for i in range(13):
            c.wait_for_osd_up(i, 60)
        c.create_ec_profile("wf84", plugin="tpu", k="8", m="4")
        c.create_pool("wfp84", "erasure", erasure_code_profile="wf84")
        rad = c.rados(timeout=120)
        io = rad.open_ioctx("wfp84")
        import os
        t0 = time.time()
        for i in range(12):
            io.write_full(f"wf{i}", os.urandom(1 << 20))
        wall = time.time() - t0
        _assert_waterfall(c, rad, wall, 12)


# ------------------------------------------------ profiler + contention
def test_dump_profile_roundtrip_and_sampler_lifecycle():
    """dump_profile returns valid folded stacks for the daemon, and the
    refcounted sampler thread dies with the cluster (tier-1 smoke for
    the no-leaked-threads teardown contract)."""
    from ceph_tpu.tools import ceph_cli
    from ceph_tpu.utils.sampler import SAMPLER_THREAD_NAME

    def sampler_threads():
        return [t for t in threading.enumerate()
                if t.name == SAMPLER_THREAD_NAME]

    assert not sampler_threads()
    with Cluster(n_osds=3, conf=make_conf()) as c:
        for i in range(3):
            c.wait_for_osd_up(i, 20)
        c.create_pool("prof", "replicated", size=2)
        io = c.rados(timeout=30).open_ioctx("prof")
        for i in range(6):
            io.write_full(f"p{i}", b"z" * 4096)
        assert len(sampler_threads()) == 1   # one thread, N daemons
        deadline = time.monotonic() + 15
        out = {}
        while time.monotonic() < deadline:
            ret, _, out = c.osds[0]._exec_command(
                {"prefix": "dump_profile"})
            assert ret == 0
            if out.get("samples", 0) > 0 and out.get("folded"):
                break
            time.sleep(0.2)
        assert out["running"] and out["samples"] > 0
        assert out["hz"] > 0
        for line in out["folded"]:
            stack, _, count = line.rpartition(" ")
            assert int(count) >= 1
            assert stack.startswith("osd0-")
            assert ";" in stack              # thread root + >=1 frame
        assert isinstance(out["self_time"], list)
        # the admin command also round-trips through the CLI
        host, port = c.mon_addr
        assert ceph_cli.main(["-m", f"{host}:{port}", "--format",
                              "json", "tell", "osd.1",
                              "dump_profile"]) == 0
        # dump_hops over the same path
        assert ceph_cli.main(["-m", f"{host}:{port}", "--format",
                              "json", "tell", "osd.1",
                              "dump_hops"]) == 0
    assert not sampler_threads(), "sampler leaked past teardown"


def test_sampler_disabled_by_config():
    from ceph_tpu.utils.sampler import SAMPLER_THREAD_NAME
    with Cluster(n_osds=2, conf=make_conf(osd_sampler_hz=0.0)) as c:
        for i in range(2):
            c.wait_for_osd_up(i, 20)
        assert not [t for t in threading.enumerate()
                    if t.name == SAMPLER_THREAD_NAME]


def test_timed_lock_counts_and_stall_flight_recording():
    from ceph_tpu.utils.flight_recorder import FlightRecorder
    from ceph_tpu.utils.locks import (ContentionStats, TimedCondition,
                                      TimedLock)
    from ceph_tpu.utils.perf import PerfCountersCollection

    coll = PerfCountersCollection()
    rec = FlightRecorder(capacity=64, name="t")
    st = ContentionStats(perf_coll=coll, recorder=rec,
                         stall_threshold_s=0.02)
    lk = TimedLock("site_a", stats=st)
    with lk:
        with lk:                              # recursion: one outer hold
            pass
    # contended acquire over the stall threshold gets flight-recorded
    def _holder():
        with lk:
            time.sleep(0.05)
    t = threading.Thread(target=_holder)
    with lk:
        t.start()
        time.sleep(0.03)                      # ensure the thread blocks
    t.join()
    cp = coll.create("contention")
    assert cp.get("site_a_acquires") == 3
    assert cp.get("stalls") >= 1
    stalls = [e for e in rec.dump() if e["kind"] == "lock_stall"]
    assert stalls and stalls[-1]["site"] == "site_a"
    assert stalls[-1]["wait_ms"] >= 20.0

    # condition wait samples land in the same site family
    cond = TimedCondition("site_b", stats=st)
    with cond:
        cond.wait(timeout=0.01)
    hist = cp.dump()["site_b_wait_us"]
    assert sum(hist["buckets"]) == 1

    # queue depth gauges: now + high-water mark
    st.register_queue("q")
    st.note_queue_depth("q", 3)
    st.note_queue_depth("q", 1)
    assert cp.get("q_depth_now") == 1 and cp.get("q_depth_hwm") == 3


# ---------------------------------------------------------------- ISSUE 8


def test_xshard_hop_wire_id_stable():
    """xshard_handoff was appended to HOP_ORDER after the ledger
    shipped: its wire id (list index) is 10, forever — the wire tuple
    is append-only, and CHARGE_ORDER exists precisely so the hop can
    still sit at its true path position."""
    assert HOP_ORDER.index("xshard_handoff") == 10
    assert set(CHARGE_ORDER) == set(HOP_ORDER)
    # presentation order: the mailbox handoff happens after the op is
    # queued for its PG and before the PG logic runs
    i = CHARGE_ORDER.index
    assert i("pg_queued") < i("xshard_handoff") < i("pg_locked")


def test_charge_places_xshard_between_queue_and_lock():
    """A ledger with a cross-shard handoff charges the mailbox dwell
    to xshard_handoff and only the post-handoff wait to pg_locked,
    with the exactness invariant intact."""
    hops = {"client_send": 0.0, "msgr_enqueue": 0.001,
            "wire_sent": 0.002, "recv": 0.010,
            "dispatch_queued": 0.011, "pg_queued": 0.012,
            "xshard_handoff": 0.030, "pg_locked": 0.031,
            "store_apply": 0.090, "commit_sent": 0.091,
            "client_complete": 0.100}
    charged = dict(charge(hops))
    assert charged["xshard_handoff"] == pytest.approx(0.018)
    assert charged["pg_locked"] == pytest.approx(0.001)
    assert sum(charged.values()) == pytest.approx(0.100)
    # and it round-trips the wire like any other hop
    e = Encoder()
    encode_ledger(e, hops)
    assert decode_ledger(Decoder(e.build())) == hops


# ---------------------------------------------------------------- ISSUE 9


def test_read_hop_wire_ids_stable():
    """The read/recovery hops were appended after the write-path
    ledger shipped: their wire ids (list indices) are 11..15 forever,
    and CHARGE_ORDER slots them at their true path positions."""
    assert [HOP_ORDER.index(h) for h in
            ("read_queued", "shard_read", "decode_dispatch",
             "decode_complete", "scrub_window")] == [11, 12, 13, 14, 15]
    assert set(CHARGE_ORDER) == set(HOP_ORDER)
    i = CHARGE_ORDER.index
    assert i("pg_locked") < i("read_queued") < i("shard_read") \
        < i("decode_dispatch") < i("decode_complete") \
        < i("store_apply")
    assert CHARGE_ORDER[-1] == "scrub_window"
    # every read/decode/scrub hop is conditional: write-path ledgers
    # never carry them and the coverage asserts must not demand them
    assert {"read_queued", "shard_read", "decode_dispatch",
            "decode_complete", "scrub_window"} <= CONDITIONAL_HOPS


# --------------------------------------------------------------- ISSUE 17


def test_peer_ack_wait_hop_wire_id_stable():
    """peer_ack_wait was appended for the async store: the primary's
    store_apply stamp moved to its LOCAL store commit, and the
    remaining acting-set ack collection charges here.  Wire id 16,
    forever; CHARGE_ORDER slots it between the local commit and the
    reply leaving."""
    assert HOP_ORDER.index("peer_ack_wait") == 16
    assert set(CHARGE_ORDER) == set(HOP_ORDER)
    i = CHARGE_ORDER.index
    assert i("store_apply") < i("peer_ack_wait") < i("commit_sent")


def test_charge_splits_local_commit_from_peer_ack_wait():
    """With an async store the local commit acks in milliseconds while
    the 12-shard ack set takes the round trip: the ledger must charge
    those separately, or the store is blamed for the network."""
    hops = {"client_send": 0.0, "msgr_enqueue": 0.001,
            "wire_sent": 0.002, "recv": 0.010,
            "dispatch_queued": 0.011, "pg_queued": 0.012,
            "pg_locked": 0.013, "store_apply": 0.020,
            "peer_ack_wait": 0.090, "commit_sent": 0.091,
            "client_complete": 0.100}
    charged = dict(charge(hops))
    assert charged["store_apply"] == pytest.approx(0.007)
    assert charged["peer_ack_wait"] == pytest.approx(0.070)
    assert sum(charged.values()) == pytest.approx(0.100)
    # a pre-split ledger (no local stamp: both hops at ack-complete)
    # degrades to peer_ack_wait == 0, never a negative interval
    hops2 = dict(hops, store_apply=0.090, peer_ack_wait=0.090)
    charged2 = dict(charge(hops2))
    assert charged2["store_apply"] == pytest.approx(0.077)
    assert charged2["peer_ack_wait"] == pytest.approx(0.0)
    # and it round-trips the wire like any other hop
    e = Encoder()
    encode_ledger(e, hops)
    assert decode_ledger(Decoder(e.build())) == hops


def test_charge_read_path_ledger():
    """A client-facing EC read ledger charges the shard fan-out wait
    to decode_dispatch and the reconstruction to decode_complete,
    with the exactness invariant intact."""
    hops = {"client_send": 0.0, "msgr_enqueue": 0.001,
            "wire_sent": 0.002, "recv": 0.010,
            "dispatch_queued": 0.011, "pg_queued": 0.012,
            "pg_locked": 0.013, "read_queued": 0.014,
            "decode_dispatch": 0.050, "decode_complete": 0.055,
            "commit_sent": 0.056, "client_complete": 0.060}
    charged = dict(charge(hops))
    assert charged["decode_dispatch"] == pytest.approx(0.036)
    assert charged["decode_complete"] == pytest.approx(0.005)
    assert "store_apply" not in charged
    assert sum(charged.values()) == pytest.approx(0.060)


def _read_and_assert_waterfall(c, rad, io, n, size):
    """The read-side acceptance invariant: serial reads' charged
    op-seconds stay within the measured client wall and the waterfall
    shares sum to 1.0."""
    t0 = time.time()
    for i in range(n):
        assert len(io.read(f"wf{i}")) == size
    wall = time.time() - t0
    d = rad.objecter.hops_read.dump()
    assert d["ops"] >= n
    assert {"recv", "pg_locked", "read_queued", "decode_dispatch",
            "decode_complete", "commit_sent",
            "client_complete"} <= set(d["hop_counts"])
    assert "store_apply" not in d["hop_counts"]  # reads never apply
    assert 0 < d["op_seconds"] <= wall * 1.25
    wf = waterfall_block(d, wall)
    assert abs(wf["sum_of_shares"] - 1.0) <= 0.05
    assert abs(wf["vs_wall"] - 1.0) <= 0.05
    assert wf["top_hop"] in HOP_ORDER
    return wf


@pytest.mark.parametrize("backend", ["classic", "crimson"])
def test_cluster_read_waterfall_invariant(backend):
    """vstart EC read-back: the client's read-side accumulator covers
    the queue/shard/decode hops and its shares sum to the measured
    read wall — under BOTH OSD execution models."""
    with Cluster(n_osds=4,
                 conf=make_conf(osd_backend=backend)) as c:
        for i in range(4):
            c.wait_for_osd_up(i, 20)
        c.create_ec_profile("rwf", plugin="tpu", k="2", m="1")
        c.create_pool("rwfp", "erasure", erasure_code_profile="rwf")
        rad = c.rados(timeout=60)
        io = rad.open_ioctx("rwfp")
        import os
        for i in range(8):
            io.write_full(f"wf{i}", os.urandom(8192))
        _read_and_assert_waterfall(c, rad, io, 8, 8192)
        # writes stayed out of the read accumulator and vice versa
        assert rad.objecter.hops.dump()["ops"] >= 8
        assert "read_queued" not in \
            rad.objecter.hops.dump()["hop_counts"]
        # each primary closed its sub-read round trips into its own
        # read-side accumulator, shard_read charged by the remote
        sub = merge_dumps([o.hops_read.dump()
                           for o in c.osds.values() if o is not None])
        assert sub["ops"] > 0
        assert "shard_read" in sub["hop_counts"]


def test_degraded_read_waterfall_one_osd_down():
    """One OSD down, no recovery window: every read still answers
    (reconstruct from surviving shards) and the read waterfall
    invariant holds on the degraded path."""
    with Cluster(n_osds=4, conf=make_conf()) as c:
        for i in range(4):
            c.wait_for_osd_up(i, 20)
        c.create_ec_profile("dwf", plugin="tpu", k="2", m="1")
        c.create_pool("dwfp", "erasure", erasure_code_profile="dwf")
        rad = c.rados(timeout=60)
        io = rad.open_ioctx("dwfp")
        import os
        for i in range(6):
            io.write_full(f"wf{i}", os.urandom(8192))
        c.kill_osd(3)
        c.wait_for_osd_down(3, 30)
        wf = _read_and_assert_waterfall(c, rad, io, 6, 8192)
        # the shard-wait (fan-out to surviving shards) leg is visible
        # in the degraded waterfall; decode itself can round to 0 on
        # 8 KiB objects but must be present
        assert wf["hop_seconds"]["decode_dispatch"] > 0.0
        assert "decode_complete" in wf["hop_seconds"]
