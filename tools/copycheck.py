#!/usr/bin/env python3
"""Static copy-discipline lint for the EC write data path.

Flags payload-copying constructs — ``bytes(``, ``.tobytes()`` and
``b"".join`` — inside the five hot-path modules the zero-copy work
covers:

    ceph_tpu/client/striper.py
    ceph_tpu/msg/messenger.py
    ceph_tpu/osd/ecbackend.py
    ceph_tpu/osd/batcher.py
    ceph_tpu/crimson/net.py

A hit is allowed only when the line carries an explicit justification
pragma::

    bytes(buf)  # copycheck: ok - <reason>

so every remaining copy in the hot path is deliberate and documented.
Comment-only and docstring occurrences are ignored.

Usage:
    python tools/copycheck.py [--root DIR] [--out COPYCHECK.json]

Exit status 0 when no unjustified hits, 1 otherwise.  The JSON report
lists both the violations and the justified allowlist so reviewers see
the full copy inventory.
"""
from __future__ import annotations

import argparse
import io
import json
import os
import re
import sys
import tokenize

HOT_MODULES = [
    "ceph_tpu/client/striper.py",
    # the frame codec and every typed message: the hop ledger rides
    # here as a trailing field, and its stamping/encoding must never
    # add a payload copy (ISSUE 7 audit)
    "ceph_tpu/msg/message.py",
    "ceph_tpu/msg/messages.py",
    "ceph_tpu/msg/messenger.py",
    "ceph_tpu/osd/ecbackend.py",
    "ceph_tpu/osd/batcher.py",
    "ceph_tpu/crimson/net.py",
    # the persistent-staging h2d path: every batched encode funnels
    # its payload through here, so a stray bytes()/tobytes() would
    # silently double the host-side cost of every device call.  The
    # device phase ledger (ISSUE 10) stamps time.time() floats along
    # this same path — stamps are scalars, never payload slices, so
    # the ledger must stay invisible to this audit
    "ceph_tpu/ops/jax_engine.py",
    # the shard-per-core hot path (ISSUE 8): every cross-shard op
    # crosses the mailbox enqueue/drain, and every encode submission
    # crosses the MPSC batcher front — both must stay copy-free
    "ceph_tpu/crimson/reactor.py",
    "ceph_tpu/crimson/osd.py",
    # the multichip dispatch layer (ISSUE 12): the sharded device_put
    # layout must add ZERO host-side payload copies beyond the staging
    # fill — shard_map/NamedSharding slice views, they must never
    # materialise per-chip copies on the host
    "ceph_tpu/parallel/mesh.py",
    # the store apply hot path (ISSUE 16): every transaction's data
    # blocks flow through _apply_ops to the block device, and the
    # store ledger stamps time.time() floats / meta ints along this
    # same path — stamps and census counts are scalars, never payload
    # slices, so the intra-transaction waterfall must add ZERO copies
    "ceph_tpu/store/blockstore.py",
    # the async rewrite of that path (ISSUE 17): WAL record framing,
    # the vectored apply-batch flush and the deferred checksum queue
    # all touch every payload block — framing headers are tiny
    # structs and the flush must write the SAME block objects it
    # buffered, never a joined copy
    "ceph_tpu/store/bluestore.py",
    # the parity-delta RMW path (ISSUE 20): Δdata staging in the tpu
    # plugin (delta_encode_batch_async) and the Δparity hand-back must
    # stay memoryview discipline end to end — one audited np.stack
    # builds the old/new column block in ecbackend (copytracked as
    # ecbackend.delta_stage), and everything after it is views: a
    # stray bytes() here would double-copy every sub-stripe overwrite
    "ceph_tpu/ec/plugins/tpu.py",
]

# constructs that materialise a full payload copy
PATTERNS = [
    (re.compile(r"(?<![\w.])bytes\("), "bytes("),
    (re.compile(r"\.tobytes\(\)"), ".tobytes()"),
    (re.compile(r"b(\"\"|'')\s*\.join"), 'b"".join'),
]

PRAGMA = re.compile(r"#\s*copycheck:\s*ok\b\s*-?\s*(.*)")


def _code_lines(source: str):
    """line number -> code text with docstring lines dropped and
    trailing comments stripped, so matches inside comments or doc
    prose don't count."""
    raw = source.splitlines()
    out = {i + 1: ln for i, ln in enumerate(raw)}
    try:
        toks = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError):
        # fall back to raw lines; better noisy than silent
        return out
    at_stmt_start = True
    for tok in toks:
        if tok.type in (tokenize.NEWLINE, tokenize.INDENT,
                        tokenize.DEDENT):
            at_stmt_start = True
            continue
        if tok.type == tokenize.COMMENT:
            # keep the code before the comment, drop the prose
            line = out.get(tok.start[0], "")
            out[tok.start[0]] = line[:tok.start[1]]
            continue
        if tok.type in (tokenize.NL, tokenize.ENCODING,
                        tokenize.ENDMARKER):
            continue
        if tok.type == tokenize.STRING and at_stmt_start:
            # docstring / bare string statement: prose, not code
            for ln in range(tok.start[0], tok.end[0] + 1):
                out.pop(ln, None)
            at_stmt_start = False
            continue
        at_stmt_start = False
    return out


def scan(root: str):
    violations, allowlisted, missing = [], [], []
    for rel in HOT_MODULES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            missing.append(rel)
            continue
        with open(path, encoding="utf-8") as f:
            source = f.read()
        raw = source.splitlines()
        code = _code_lines(source)
        for lineno, text in sorted(code.items()):
            for rx, label in PATTERNS:
                if not rx.search(text):
                    continue
                raw_line = raw[lineno - 1] if lineno <= len(raw) else ""
                m = PRAGMA.search(raw_line)
                entry = {"file": rel, "line": lineno,
                         "pattern": label,
                         "text": raw_line.strip()[:160]}
                if m:
                    entry["reason"] = m.group(1).strip()
                    allowlisted.append(entry)
                else:
                    violations.append(entry)
    return violations, allowlisted, missing


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--out", default=None,
                    help="write the JSON report here as well")
    args = ap.parse_args(argv)
    violations, allowlisted, missing = scan(args.root)
    report = {
        "threshold": 0.6,
        "flagged": violations,
        "allowlisted": allowlisted,
        "missing_modules": missing,
        "error": "",
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    print(text)
    if violations:
        print(f"\ncopycheck: {len(violations)} unjustified copy "
              f"site(s) in hot-path modules", file=sys.stderr)
        return 1
    print(f"\ncopycheck: clean "
          f"({len(allowlisted)} justified copy sites)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
