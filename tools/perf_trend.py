#!/usr/bin/env python3
"""Perf-trend regression gate over the bench history.

Diffs a FRESH bench run's k8m4 attribution JSON (the
``cluster k8m4 write per-stage time attribution`` object bench.py
prints, now carrying ``critical_path`` and
``device_encode_fraction``) against the committed ``BENCH_r0*.json``
history and fails loudly on:

- **routing collapse** — the r05 failure mode: the codec boundary
  sustains a large device speedup while the cluster routes (nearly)
  every encode to the CPU twin because the crossover was pinned above
  every group size.  Caught structurally: ``device_encode_fraction``
  below the floor while the run's own calibration expected the device
  to win (``expect_device``), or while the same run's codec-boundary
  headline shows the device clearly ahead.
- **per-stage regression** — a pipeline stage's share of the write
  wall grows by more than the tolerance vs the most recent history
  round that recorded an attribution breakdown.
- **throughput regression** — the cluster k8m4 ``vs_baseline`` write
  ratio drops below ``ratio_tol`` x the best comparable history round
  (matched on the k=8 m=4 cluster config).
- **hop p99 regression** — a wire hop's p99 latency in the
  attribution's ``waterfall`` block blows past the most recent
  history round that recorded one.  History rounds predating the hop
  ledger carry no waterfall and the check is silently skipped.
- **read-path hop p99 regression** — same budget applied to the
  ``read_waterfall`` block (the client-facing read ledger: queue /
  shard_read / decode hops).  Rounds predating the read ledger
  silently skip.
- **device-phase p99 regression** — the same budget applied to the
  ``device_waterfall`` block (the sub-dispatch phase ledger:
  stage_acquire / h2d / compute fence / d2h / deliver).  Rounds
  predating the device ledger silently skip, as does a fresh run
  that routed no groups to the device.
- **store-phase p99 regression** — the same budget applied another
  layer down, to the ``store_waterfall`` block (the intra-transaction
  ledger below the ``store_apply`` hop: journal append/fsync, alloc,
  data write, compress, kv commit).  History rounds predating the
  store ledger carry no store_waterfall block and self-skip, as does
  a fresh run that applied no store transactions.
- **pipeline-overlap collapse** — the overlap engine's verdict
  (``pipeline_overlap_frac``: fraction of the device window where
  group N+1's h2d hides under group N's compute) falls below
  ``overlap_tol`` x the best overlap any history round achieved.
  Gated on the fresh run actually expecting / using the device: a
  CPU-only box reports ``expect_device`` false and zero
  ``device_reqs`` and must NOT trip on its overlap of 0.
- **rebuild throughput floor** — the ``OSD rebuild MB/s`` ratio from
  the rebuild config must hold >= ``ratio_tol`` x the best comparable
  (k=8 m=4) history round; OSD-loss recovery is a first-class path
  now that decode rides the batched device pipeline.
- **decode routing collapse** — the encode collapse check applied to
  the collect-time decode router: ``device_decode_fraction`` below
  the floor while the run's calibration expected the device to win
  means every recovery decode rode the CPU twin (the ``dec_route_*``
  verdict counters name the reason).  Runs whose calibration did not
  pin for the device (CPU-only box) self-skip.
- **SLO regression** — the attribution's ``slo`` block (per-class
  error-budget burn merged across every OSD) must show ZERO
  client-class burn on a bench run (bench runs are fault-free), and
  no recovery/scrub-class *errors* where the most recent
  SLO-carrying history round had none.  Rounds predating the SLO
  engine silently skip.
- **load p99 regression** — the ``open-loop load attribution``
  record from the load config: each client class's p99 must stay
  within the hop-p99 budget (1.5x + 1 ms) of the most recent
  load-carrying history round.  Rounds predating the load harness
  carry no load record and the check silently skips.  Independent of
  history, a fresh load record showing client errors or client-class
  SLO burn fails outright — the harness's own acceptance re-asserted
  at the gate.
- **crimson ladder regression** — the cluster_scaling record's full
  classic/crimson sides: crimson must be >= classic at EVERY rung of
  the 1/4/16/64 client ladder (ISSUE 13's tentpole — the 64-client
  fan-in was the one rung classic still won).
- **multichip mesh floor** — the ``multichip mesh attribution``
  record from the multichip config: the batcher-routed sharded
  encode must beat its device-count floor vs single-chip (>=0.9x on
  1 device, >=1.5x on >=4), hold ``ratio_tol`` x the best history
  round's sharded GiB/s, and show one per-device ledger lane per
  mesh chip.  History rounds without a mesh block (pre-mesh rounds)
  are silently skipped.
- **selftune floor** — the ``closed-loop selftune attribution``
  record from the selftune config (ISSUE 15): with the autotuner
  enabled the client ladder may not lose ANY rung to the static
  defaults run in the same process (guarded rollback means the
  controller's worst case is "changed nothing"), and zero guard
  trips (SLO burn / overlap collapse / breaker) may fire while it
  tunes.  Compared within one fresh run, so no machine-speed
  tolerance is owed; runs without a selftune record self-skip.

History files are ``{"n", "cmd", "rc", "tail", "parsed"}`` wrappers
around a captured bench stdout; metric records are re-extracted from
the embedded JSON lines in ``tail`` (r01-r03 predate the cluster
configs and r05's tail truncates the attribution line — missing
records are tolerated, the checks that need them are skipped).

Exit codes: 0 pass, 1 regression, 2 no data / parse error.
``bench.py --assert-floor`` imports :func:`check` directly and runs
the same gate on the in-process attribution dict.
"""
from __future__ import annotations

import argparse
import glob as globlib
import json
import os
import sys
from typing import Dict, List, Optional

_ATTRIB_PREFIX = "cluster k8m4 write per-stage time attribution"
_CLUSTER_PREFIX = "cluster write MB/s"
_HEADLINE_PREFIX = "EC encode GiB/s at the codec boundary"
_SCALING_PREFIX = "cluster write scaling"
_REBUILD_PREFIX = "OSD rebuild MB/s"
_REBUILD_ATTRIB_PREFIX = "rebuild decode attribution"
_MESH_ATTRIB_PREFIX = "multichip mesh attribution"
_LOAD_PREFIX = "open-loop load attribution"
_SELFTUNE_PREFIX = "closed-loop selftune attribution"
_STORE_LADDER_PREFIX = "store ladder write MB/s"
_RMW_PREFIX = "rmw overwrite MB/s"
_K8M4_MARK = "k=8 m=4"

# defaults, overridable from the CLI
STAGE_TOL = 0.15          # absolute share-of-wall growth allowed
RATIO_TOL = 0.8           # fresh ratio must be >= tol * best history
MIN_DEVICE_FRACTION = 0.5  # below this the routing collapsed
HEADLINE_DEVICE_WIN = 2.0  # codec vs_baseline that proves the device
HOP_P99_FACTOR = 1.5       # fresh hop p99 may grow to this x history
HOP_P99_SLACK_S = 1e-3     # ...and must also grow by this much abs.
SCALING_TOL = 0.8          # 16-client MB/s >= tol * best history
OVERLAP_TOL = 0.5          # fresh overlap frac >= tol * best history
SELFTUNE_FLOOR = 1.0       # tuned MB/s >= floor * static, every rung
STORE_LADDER_FLOOR = 0.85  # bluestore MB/s >= floor * blockstore at
#                            EVERY (queue depth, txn size) rung; the
#                            slack absorbs single-process IO noise
#                            (same spirit as RATIO_TOL), the mean
#                            ratio in the record stays the headline
RMW_FLOOR = 1.0            # delta-path MB/s >= floor * forced-full at
#                            EVERY overwrite size (equality passes:
#                            the crossover learner's worst case is
#                            "route to the full path", so losing a
#                            size outright means the delta path fired
#                            where it should not have; the >=2x
#                            small-write win is the record's
#                            vs_baseline headline)
RMW_MIN_DELTA_FRACTION = 0.25  # share of RMWs that must actually take
#                            the delta path in the delta run: 2 of the
#                            3 size classes are delta-eligible, so a
#                            fraction under this means eligibility or
#                            routing collapsed and the bench compared
#                            full vs full


def _records_from_text(text: str) -> List[Dict]:
    """Every parseable JSON object line carrying a "metric" field."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except (ValueError, TypeError):
            continue
        if isinstance(obj, dict) and "metric" in obj:
            out.append(obj)
    return out


def _round_records(round_obj: Dict) -> List[Dict]:
    recs = _records_from_text(round_obj.get("tail", "") or "")
    parsed = round_obj.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed and \
            not any(r.get("metric") == parsed["metric"] for r in recs):
        recs.append(parsed)
    return recs


def load_history(paths: List[str]) -> List[Dict]:
    """-> [{"n": int, "path": str, "records": [...]}] sorted by n."""
    rounds = []
    for p in sorted(paths):
        try:
            with open(p) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            raise SystemExit(f"perf_trend: unreadable history "
                             f"{p}: {e}")
        rounds.append({"n": int(obj.get("n", 0)), "path": p,
                       "records": _round_records(obj)})
    rounds.sort(key=lambda r: r["n"])
    return rounds


def default_history_paths() -> List[str]:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return sorted(globlib.glob(os.path.join(root, "BENCH_r0*.json")))


def _pick(records: List[Dict], prefix: str,
          mark: Optional[str] = None) -> Optional[Dict]:
    for r in records:
        m = r.get("metric", "")
        if m.startswith(prefix) and (mark is None or mark in m):
            return r
    return None


def load_fresh(path: str) -> List[Dict]:
    """Fresh input: a bare attribution object, a history-style
    wrapper, or a raw bench stdout log — always -> metric records."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise SystemExit(f"perf_trend: unreadable fresh input "
                         f"{path}: {e}")
    try:
        obj = json.loads(text)
    except ValueError:
        return _records_from_text(text)
    if isinstance(obj, dict) and "tail" in obj:
        return _round_records(obj)
    if isinstance(obj, dict) and "metric" in obj:
        return [obj]
    if isinstance(obj, list):
        return [r for r in obj
                if isinstance(r, dict) and "metric" in r]
    return _records_from_text(text)


# ---------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------
def check(attribution: Optional[Dict], history: List[Dict],
          fresh_ratio: Optional[float] = None,
          fresh_headline_ratio: Optional[float] = None,
          fresh_scaling: Optional[Dict] = None,
          fresh_ladder: Optional[Dict] = None,
          fresh_load: Optional[Dict] = None,
          fresh_rebuild: Optional[Dict] = None,
          fresh_mesh: Optional[Dict] = None,
          fresh_selftune: Optional[Dict] = None,
          fresh_store_ladder: Optional[Dict] = None,
          fresh_rmw: Optional[Dict] = None,
          stage_tol: float = STAGE_TOL,
          ratio_tol: float = RATIO_TOL,
          min_device_fraction: float = MIN_DEVICE_FRACTION,
          hop_p99_factor: float = HOP_P99_FACTOR,
          scaling_tol: float = SCALING_TOL,
          overlap_tol: float = OVERLAP_TOL) \
        -> List[Dict]:
    """-> findings ``[{"check", "severity", "message"}]``; empty =
    pass.  ``attribution`` is the fresh run's attribution object (may
    be None — only the ratio check can then run); ``fresh_ratio`` the
    fresh cluster-write vs_baseline; ``fresh_headline_ratio`` the
    fresh codec-boundary vs_baseline (device proof for the collapse
    check when no calibration pin was recorded); ``fresh_scaling``
    the crimson client-ladder dict ({"1": MB/s, ...}) from the
    cluster_scaling config — compared at the 16-client rung against
    the best history round that recorded one (rounds predating the
    ladder silently skip the check); ``fresh_ladder`` both sides of
    that ladder ({"classic": {...}, "crimson": {...}}), feeding the
    every-rung crimson>=classic assert; ``fresh_load`` the load
    config's ``open-loop load attribution`` record, feeding the
    per-class p99 budget vs the latest load-carrying history round
    and the zero-client-error / zero-client-burn re-assert;
    ``fresh_rebuild`` the rebuild config's decode-side attribution
    object, feeding the rebuild throughput floor and the decode
    routing-collapse check; ``fresh_selftune`` the selftune config's
    static-vs-tuned ladder + tuner audit block, feeding the
    tuned>=static every-rung floor and the zero-guard-trip
    re-assert; ``fresh_store_ladder`` the store_ladder config's
    single-store microbench record, feeding the bluestore>=blockstore
    every-rung floor (ISSUE 17); ``fresh_rmw`` the rmw config's
    delta-vs-forced-full overwrite record, feeding the every-size
    delta>=full floor, the delta-path routing-collapse check, and the
    forced-off control-leak assert (ISSUE 20)."""
    findings: List[Dict] = []

    # -- async-store top-hop gate (ISSUE 17) --------------------------
    # With osd_objectstore=bluestore the commit ack rides WAL group
    # commit and apply runs off the PG-lock path: a fresh waterfall
    # still naming store_apply the top hop means the deferred
    # pipeline is not deferring (applier starved, deferred queue
    # saturated at depth, or readers serializing on the apply
    # barrier).
    if attribution is not None \
            and attribution.get("osd_objectstore") == "bluestore":
        wf = attribution.get("waterfall")
        if isinstance(wf, dict) and wf.get("top_hop") == "store_apply":
            findings.append({
                "check": "store-top-hop", "severity": "fail",
                "message":
                    "osd_objectstore=bluestore yet the fresh "
                    "waterfall still names store_apply as top_hop — "
                    "the WAL/deferred-apply pipeline is not taking "
                    "the store off the critical path (check the "
                    "store_waterfall block: deferred_queue share, "
                    "wal group_syncs vs txns, and "
                    "bluestore_deferred_queue_depth backpressure)"})

    # -- routing collapse (the r05 signature) -------------------------
    if attribution is not None:
        frac = attribution.get("device_encode_fraction")
        if frac is None:
            routing = attribution.get("routing") or {}
            dev = routing.get("device_reqs")
            cpu = routing.get("cpu_twin_reqs")
            if dev is not None and cpu is not None and dev + cpu > 0:
                frac = dev / (dev + cpu)
        expect = attribution.get("expect_device")
        device_proven = expect is True or (
            expect is None and fresh_headline_ratio is not None
            and fresh_headline_ratio >= HEADLINE_DEVICE_WIN)
        if frac is not None and device_proven \
                and frac < min_device_fraction:
            why = "calibration pinned the crossover for the device" \
                if expect is True else \
                (f"the codec boundary sustains "
                 f"{fresh_headline_ratio:.1f}x baseline on device")
            findings.append({
                "check": "routing-collapse", "severity": "fail",
                "message":
                    f"device_encode_fraction {frac:.3f} < "
                    f"{min_device_fraction:.2f} while {why} — "
                    f"encode traffic is misrouted to the CPU twin "
                    f"(r05-style routing collapse: the crossover "
                    f"threshold sits above every group the cluster "
                    f"forms; check ec_tpu_min_device_bytes pinning "
                    f"and the ec_device route_* reason counters)"})

    # -- per-stage share regression -----------------------------------
    hist_att = None
    for rnd in reversed(history):
        hist_att = _pick(rnd["records"], _ATTRIB_PREFIX)
        if hist_att is not None:
            break
    if attribution is not None and hist_att is not None:
        old_st = hist_att.get("stages") or {}
        new_st = attribution.get("stages") or {}
        old_wall = sum(old_st.values())
        new_wall = sum(new_st.values())
        if old_wall > 0 and new_wall > 0:
            for s in sorted(set(old_st) | set(new_st)):
                old_share = old_st.get(s, 0.0) / old_wall
                new_share = new_st.get(s, 0.0) / new_wall
                if new_share > old_share + stage_tol:
                    findings.append({
                        "check": "stage-regression",
                        "severity": "fail",
                        "message":
                            f"stage {s!r} grew to {new_share:.0%} of "
                            f"the write wall (history "
                            f"{old_share:.0%}, tolerance "
                            f"+{stage_tol:.0%})"})

    # -- per-hop p99 budgets (waterfall + read_waterfall blocks) ------
    # A waterfall block only exists from the hop-ledger rounds on
    # (read_waterfall one PR later); older history (and fresh runs
    # with the ledger disabled) simply lack it and the check
    # self-skips — no data is never a failure.
    def _hist_block(key: str) -> Optional[Dict]:
        for rnd in reversed(history):
            rec = _pick(rnd["records"], _ATTRIB_PREFIX)
            if rec is not None and isinstance(rec.get(key), dict) \
                    and isinstance(rec[key].get("p99_s"), dict):
                return rec[key]
        return None

    for key, label in (("waterfall", "write"),
                       ("read_waterfall", "read")):
        hist_wf = _hist_block(key)
        fresh_wf = (attribution or {}).get(key) \
            if attribution is not None else None
        if not isinstance(fresh_wf, dict) or hist_wf is None:
            continue
        old_p99 = hist_wf.get("p99_s") or {}
        new_p99 = fresh_wf.get("p99_s") or {}
        for hop in sorted(new_p99):
            old = old_p99.get(hop)
            new = new_p99.get(hop)
            if not isinstance(old, (int, float)) \
                    or not isinstance(new, (int, float)):
                continue
            if new > old * hop_p99_factor \
                    and new - old > HOP_P99_SLACK_S:
                findings.append({
                    "check": f"{label}-hop-p99-regression",
                    "severity": "fail",
                    "message":
                        f"{label}-path hop {hop!r} p99 "
                        f"{new * 1e3:.2f} ms > "
                        f"{hop_p99_factor:.1f} x history "
                        f"{old * 1e3:.2f} ms ({key} budget)"})

    # -- device-phase p99 budgets (device_waterfall block) ------------
    # The wire-hop budget applied one layer down: the sub-dispatch
    # phase ledger stamped inside the batcher/engine (stage_acquire /
    # h2d / compute fence / d2h / deliver).  Rounds predating the
    # device ledger carry no device_waterfall block and self-skip; a
    # fresh run that routed zero groups to the device (CPU-only box)
    # has no phase p99s worth budgeting and also self-skips.
    fresh_dwf = (attribution or {}).get("device_waterfall") \
        if attribution is not None else None
    hist_dwf = _hist_block("device_waterfall")
    if isinstance(fresh_dwf, dict) and fresh_dwf.get("groups") \
            and hist_dwf is not None:
        old_p99 = hist_dwf.get("p99_s") or {}
        new_p99 = fresh_dwf.get("p99_s") or {}
        for phase in sorted(new_p99):
            old = old_p99.get(phase)
            new = new_p99.get(phase)
            if not isinstance(old, (int, float)) \
                    or not isinstance(new, (int, float)):
                continue
            if new > old * hop_p99_factor \
                    and new - old > HOP_P99_SLACK_S:
                findings.append({
                    "check": "device-phase-p99-regression",
                    "severity": "fail",
                    "message":
                        f"device phase {phase!r} p99 "
                        f"{new * 1e3:.2f} ms > "
                        f"{hop_p99_factor:.1f} x history "
                        f"{old * 1e3:.2f} ms (device_waterfall "
                        f"budget)"})

    # -- store-phase p99 budgets (store_waterfall block) --------------
    # (ISSUE 16) The hop budget applied below the store_apply wall:
    # the intra-transaction phase ledger stamped inside the
    # ObjectStore seams (journal append / journal fsync / alloc /
    # data write / compress / kv commit / flush).  Rounds predating
    # the store ledger carry no store_waterfall block and self-skip;
    # a fresh run that applied no store transactions has no phase
    # p99s worth budgeting and also self-skips.
    fresh_swf = (attribution or {}).get("store_waterfall") \
        if attribution is not None else None
    hist_swf = _hist_block("store_waterfall")
    if isinstance(fresh_swf, dict) and fresh_swf.get("txns") \
            and hist_swf is not None:
        old_p99 = hist_swf.get("p99_s") or {}
        new_p99 = fresh_swf.get("p99_s") or {}
        for phase in sorted(new_p99):
            old = old_p99.get(phase)
            new = new_p99.get(phase)
            if not isinstance(old, (int, float)) \
                    or not isinstance(new, (int, float)):
                continue
            if new > old * hop_p99_factor \
                    and new - old > HOP_P99_SLACK_S:
                findings.append({
                    "check": "store-phase-p99-regression",
                    "severity": "fail",
                    "message":
                        f"store phase {phase!r} p99 "
                        f"{new * 1e3:.2f} ms > "
                        f"{hop_p99_factor:.1f} x history "
                        f"{old * 1e3:.2f} ms (store_waterfall "
                        f"budget)"})

    # -- pipeline-overlap collapse ------------------------------------
    # The overlap engine's headline: the fraction of the per-device
    # window where the next group's h2d transfer hides under the
    # current group's compute.  Losing it (double-buffering broken,
    # staging ring serialized) shows up long before throughput does.
    # Only meaningful when the run actually drives the device — a
    # CPU-only box legitimately reports overlap 0 alongside
    # expect_device False / zero device_reqs and must NOT trip.
    # History rounds without an overlap verdict self-skip.
    if isinstance(fresh_dwf, dict):
        new_frac = fresh_dwf.get("pipeline_overlap_frac")
        expect = (attribution or {}).get("expect_device")
        routing = (attribution or {}).get("routing") or {}
        dev_reqs = routing.get("device_reqs")
        device_active = expect is True or (
            isinstance(dev_reqs, (int, float)) and dev_reqs > 0)
        best_frac = None
        for rnd in history:
            rec = _pick(rnd["records"], _ATTRIB_PREFIX)
            dwf = rec.get("device_waterfall") \
                if rec is not None else None
            v = dwf.get("pipeline_overlap_frac") \
                if isinstance(dwf, dict) else None
            if isinstance(v, (int, float)) and v > 0:
                best_frac = v if best_frac is None \
                    else max(best_frac, v)
        if device_active and best_frac is not None \
                and isinstance(new_frac, (int, float)) \
                and new_frac < overlap_tol * best_frac:
            findings.append({
                "check": "overlap-collapse", "severity": "fail",
                "message":
                    f"pipeline_overlap_frac {new_frac:.3f} < "
                    f"{overlap_tol:.2f} x best history "
                    f"{best_frac:.3f} — h2d no longer hides under "
                    f"compute (bounding phase "
                    f"{fresh_dwf.get('bounding_phase')!r}; check the "
                    f"staging ring depth and the async dispatch "
                    f"lead)"})

    # -- SLO regression (per-class error-budget burn) -----------------
    # Bench runs are fault-free: ANY client-class burn in the fresh
    # run is a regression outright.  Recovery/scrub classes tolerate
    # latency breaches (machine-speed noise) but not errors appearing
    # where the most recent SLO-carrying history round had none.
    # Rounds predating the SLO engine carry no `slo` block and the
    # history half self-skips.
    fresh_slo = (attribution or {}).get("slo") \
        if attribution is not None else None
    if isinstance(fresh_slo, dict):
        for cls in ("client_read", "client_write"):
            row = fresh_slo.get(cls) or {}
            burn = row.get("burn", 0.0)
            if isinstance(burn, (int, float)) and burn > 0:
                findings.append({
                    "check": "slo-regression", "severity": "fail",
                    "message":
                        f"{cls} burned error budget on a fault-free "
                        f"bench run (burn {burn:.3f}, "
                        f"{row.get('breaches', 0)} breaches / "
                        f"{row.get('errors', 0)} errors over "
                        f"{row.get('ops', 0)} ops)"})
        hist_slo = None
        for rnd in reversed(history):
            rec = _pick(rnd["records"], _ATTRIB_PREFIX)
            if rec is not None and isinstance(rec.get("slo"), dict):
                hist_slo = rec["slo"]
                break
        if hist_slo is not None:
            for cls in ("recovery", "scrub"):
                new_err = (fresh_slo.get(cls) or {}).get("errors", 0)
                old_err = (hist_slo.get(cls) or {}).get("errors", 0)
                if isinstance(new_err, (int, float)) and new_err > 0 \
                        and not old_err:
                    findings.append({
                        "check": "slo-regression", "severity": "fail",
                        "message":
                            f"{cls}-class errors appeared "
                            f"({new_err}) where the last SLO-carrying "
                            f"history round had none"})

    # -- cluster throughput ratio regression --------------------------
    if fresh_ratio is not None:
        best = None
        for rnd in history:
            rec = _pick(rnd["records"], _CLUSTER_PREFIX, _K8M4_MARK)
            if rec and isinstance(rec.get("vs_baseline"),
                                  (int, float)):
                v = float(rec["vs_baseline"])
                best = v if best is None else max(best, v)
        if best is not None and fresh_ratio < ratio_tol * best:
            findings.append({
                "check": "throughput-regression", "severity": "fail",
                "message":
                    f"cluster k8m4 write at {fresh_ratio:.3f}x "
                    f"baseline < {ratio_tol:.2f} x best history "
                    f"{best:.3f}x"})

    # -- concurrency-scaling regression (16-client rung) --------------
    # History rounds predating the cluster_scaling ladder record no
    # scaling metric; the check self-skips until one exists.
    if fresh_scaling:
        fresh16 = fresh_scaling.get("16")
        best16 = None
        for rnd in history:
            rec = _pick(rnd["records"], _SCALING_PREFIX)
            if rec is None:
                continue
            v = ((rec.get("crimson") or {}).get("clients")
                 or {}).get("16")
            if isinstance(v, (int, float)):
                best16 = v if best16 is None else max(best16, v)
        if isinstance(fresh16, (int, float)) and best16 is not None \
                and fresh16 < scaling_tol * best16:
            findings.append({
                "check": "scaling-regression", "severity": "fail",
                "message":
                    f"16-client cluster write at {fresh16:.1f} MB/s "
                    f"< {scaling_tol:.2f} x best history "
                    f"{best16:.1f} MB/s (shard-per-core concurrency "
                    f"ladder)"})

    # -- crimson>=classic ladder (every rung) -------------------------
    # (ISSUE 13) The tentpole's acceptance: with the 64-client fan-in
    # fix and QoS on the reactor path, the default backend may not
    # lose ANY rung of the concurrency ladder to classic.  Compared
    # within one fresh run (same box, same minute), so no machine-
    # speed tolerance is owed.
    if fresh_ladder:
        cl_side = fresh_ladder.get("classic") or {}
        cr_side = fresh_ladder.get("crimson") or {}
        for rung in sorted(set(cl_side) & set(cr_side),
                           key=lambda r: int(r)):
            old = cl_side.get(rung)
            new = cr_side.get(rung)
            if not isinstance(old, (int, float)) \
                    or not isinstance(new, (int, float)):
                continue
            if new < old:
                findings.append({
                    "check": "crimson-ladder-regression",
                    "severity": "fail",
                    "message":
                        f"crimson {new:.1f} MB/s < classic "
                        f"{old:.1f} MB/s at the {rung}-client rung "
                        f"— the reactor path lost a rung of the "
                        f"concurrency ladder (check the fan-in "
                        f"batching, connection-shard affinity and "
                        f"admission backpressure)"})

    # -- open-loop load: per-class p99 budget + QoS re-assert ---------
    # History rounds predating the load harness record no load
    # attribution and the p99 half self-skips; the error/burn half is
    # absolute (the harness promised zero) and needs no history.
    if fresh_load:
        errs = fresh_load.get("errors")
        if isinstance(errs, (int, float)) and errs > 0:
            findings.append({
                "check": "load-client-errors", "severity": "fail",
                "message":
                    f"open-loop load run leaked {int(errs)} client "
                    f"errors (the harness promises zero across "
                    f"every gateway)"})
        burn = (fresh_load.get("contention") or {}) \
            .get("client_burn") or {}
        for cls, b in sorted(burn.items()):
            if isinstance(b, (int, float)) and b > 0:
                findings.append({
                    "check": "load-qos-regression", "severity": "fail",
                    "message":
                        f"{cls} burned error budget ({b:.3f}) under "
                        f"injected recovery contention — QoS "
                        f"demotion failed to protect the client "
                        f"class"})
        hist_load = None
        for rnd in reversed(history):
            rec = _pick(rnd["records"], _LOAD_PREFIX)
            if rec is not None and \
                    isinstance(rec.get("latency_ms"), dict):
                hist_load = rec["latency_ms"]
                break
        new_lat = fresh_load.get("latency_ms") or {}
        if hist_load is not None:
            for cls in sorted(new_lat):
                old = (hist_load.get(cls) or {}).get("p99_ms")
                new = (new_lat.get(cls) or {}).get("p99_ms")
                if not isinstance(old, (int, float)) \
                        or not isinstance(new, (int, float)):
                    continue
                if new > old * hop_p99_factor \
                        and new - old > HOP_P99_SLACK_S * 1e3:
                    findings.append({
                        "check": "load-p99-regression",
                        "severity": "fail",
                        "message":
                            f"open-loop load {cls} p99 {new:.2f} ms "
                            f"> {hop_p99_factor:.1f} x history "
                            f"{old:.2f} ms (+1 ms slack) under the "
                            f"same offered load"})

    # -- rebuild throughput floor + decode routing collapse -----------
    # (ISSUE 11) ``fresh_rebuild`` is the rebuild config's
    # decode-side attribution object.  The floor mirrors the
    # write-ratio gate over the "OSD rebuild MB/s" history records
    # (k=8 m=4 marked runs only — the line predates the device
    # decode pipeline, so history exists to hold it to); the routing
    # check is the r05 collapse signature applied to the
    # collect-time decode router, gated on this run's own
    # calibration expecting the device to win.
    if fresh_rebuild is not None:
        rb_ratio = fresh_rebuild.get("vs_baseline")
        best = None
        for rnd in history:
            rec = _pick(rnd["records"], _REBUILD_PREFIX, _K8M4_MARK)
            if rec and isinstance(rec.get("vs_baseline"),
                                  (int, float)):
                v = float(rec["vs_baseline"])
                best = v if best is None else max(best, v)
        if isinstance(rb_ratio, (int, float)) and best is not None \
                and rb_ratio < ratio_tol * best:
            findings.append({
                "check": "rebuild-throughput-regression",
                "severity": "fail",
                "message":
                    f"OSD rebuild at {rb_ratio:.3f}x baseline < "
                    f"{ratio_tol:.2f} x best history {best:.3f}x "
                    f"(k8m4 OSD-loss recovery floor)"})
        frac = fresh_rebuild.get("device_decode_fraction")
        if frac is None:
            routing = fresh_rebuild.get("routing") or {}
            dev = routing.get("device_reqs")
            cpu = routing.get("cpu_twin_reqs")
            if dev is not None and cpu is not None and dev + cpu > 0:
                frac = dev / (dev + cpu)
        if fresh_rebuild.get("expect_device") is True \
                and frac is not None and frac < min_device_fraction:
            findings.append({
                "check": "dec-routing-collapse", "severity": "fail",
                "message":
                    f"device_decode_fraction {frac:.3f} < "
                    f"{min_device_fraction:.2f} while calibration "
                    f"pinned the crossover for the device — recovery "
                    f"decode traffic is misrouted to the CPU twin "
                    f"(dec_route_* verdicts: "
                    f"{fresh_rebuild.get('dec_routes')}; check the "
                    f"decode crossover seed and "
                    f"ec_tpu_min_device_bytes pinning)"})

    # -- multichip mesh throughput floor ------------------------------
    # (ISSUE 12) ``fresh_mesh`` is the multichip config's attribution
    # record: the batcher-routed sharded-vs-single-chip speedup and
    # its device-count-dependent floor (>=0.9x on 1 device where the
    # mesh must be pure fallback, >=1.5x on >=4 where ICI must pay).
    # History rounds are compared on the sharded throughput itself;
    # rounds without a mesh block (pre-PR-12) are silently skipped.
    if fresh_mesh is not None:
        sp = fresh_mesh.get("speedup")
        fl = fresh_mesh.get("floor")
        if isinstance(sp, (int, float)) and \
                isinstance(fl, (int, float)) and sp < fl:
            nd = fresh_mesh.get("n_devices")
            findings.append({
                "check": "mesh-floor", "severity": "fail",
                "message":
                    f"sharded/single-chip speedup {sp:.3f}x < floor "
                    f"{fl:.2f}x on {nd} device(s) — the mesh "
                    f"dispatch path costs more than it pays"})
        gbps = fresh_mesh.get("sharded_gbps")
        best = None
        for rnd in history:
            rec = _pick(rnd["records"], _MESH_ATTRIB_PREFIX)
            if rec and rec.get("mesh") and \
                    isinstance(rec.get("sharded_gbps"), (int, float)):
                v = float(rec["sharded_gbps"])
                best = v if best is None else max(best, v)
        if isinstance(gbps, (int, float)) and best is not None \
                and gbps < ratio_tol * best:
            findings.append({
                "check": "mesh-throughput-regression",
                "severity": "fail",
                "message":
                    f"batcher-routed mesh encode at {gbps:.3f} GiB/s "
                    f"< {ratio_tol:.2f} x best history {best:.3f} "
                    f"GiB/s"})
        nd = fresh_mesh.get("n_devices")
        lanes = fresh_mesh.get("device_lanes")
        if isinstance(nd, int) and nd > 1 and \
                isinstance(lanes, int) and lanes < nd:
            findings.append({
                "check": "mesh-lane-collapse", "severity": "fail",
                "message":
                    f"only {lanes} per-device ledger lane(s) for a "
                    f"{nd}-device mesh — some chips produced no "
                    f"waterfall evidence (sharding or ledger fanout "
                    f"broke)"})

    # -- closed-loop selftune floor + guard-trip re-assert ------------
    # (ISSUE 15) ``fresh_selftune`` carries the static-vs-tuned
    # client ladder measured in ONE process (same box, same minute —
    # no machine-speed tolerance owed) plus the merged dump_tuner
    # audit block.  Guarded rollback means the controller's worst
    # case is "changed nothing": a tuned rung below its static twin,
    # or ANY guard trip (SLO burn / overlap collapse / breaker)
    # while tuning, is a controller regression outright.
    if fresh_selftune is not None:
        ladder = fresh_selftune.get("ladder") or {}
        st_side = ladder.get("static") or {}
        tn_side = ladder.get("tuned") or {}
        for rung in sorted(set(st_side) & set(tn_side),
                           key=lambda r: int(r)):
            old = st_side.get(rung)
            new = tn_side.get(rung)
            if not isinstance(old, (int, float)) \
                    or not isinstance(new, (int, float)):
                continue
            if new < SELFTUNE_FLOOR * old:
                findings.append({
                    "check": "selftune-regression",
                    "severity": "fail",
                    "message":
                        f"self-tuned {new:.1f} MB/s < static "
                        f"{old:.1f} MB/s at the {rung}-client rung — "
                        f"the autotuner made the cluster slower than "
                        f"leaving the knobs alone (check the tuner "
                        f"block's kept/rolled_back decisions and the "
                        f"hysteresis band)"})
        tuner = fresh_selftune.get("tuner") or {}
        trips = tuner.get("guard_trips")
        guards = tuner.get("guards") or []
        if (isinstance(trips, (int, float)) and trips > 0) or guards:
            why = sorted(set(guards)) if guards \
                else "reasons not recorded"
            findings.append({
                "check": "selftune-guard-trip", "severity": "fail",
                "message":
                    f"{int(trips or len(guards))} guard trip(s) "
                    f"fired while self-tuning ({why}) — a probe "
                    f"pushed the cluster into SLO burn / overlap "
                    f"collapse before the rollback caught it; the "
                    f"controller must stay inside the guard envelope "
                    f"on a fault-free bench run"})

    # -- store-ladder bluestore>=blockstore floor (ISSUE 17) ----------
    # ``fresh_store_ladder`` carries the single-store microbench
    # (memstore / blockstore / bluestore at qd 1/8/32, 64 KiB and
    # 1 MiB txns) measured in ONE process, so no machine-speed
    # tolerance is owed: the async rewrite's worst case is the
    # synchronous discipline it replaced — any rung where bluestore
    # loses to blockstore is a regression outright.
    if fresh_store_ladder is not None:
        ladder = fresh_store_ladder.get("ladder") or {}
        blue = ladder.get("bluestore") or {}
        block = ladder.get("blockstore") or {}
        for rung in sorted(set(blue) & set(block)):
            old = block.get(rung)
            new = blue.get(rung)
            if not isinstance(old, (int, float)) \
                    or not isinstance(new, (int, float)):
                continue
            if new < STORE_LADDER_FLOOR * old:
                findings.append({
                    "check": "store-ladder-regression",
                    "severity": "fail",
                    "message":
                        f"bluestore {new:.1f} MB/s < blockstore "
                        f"{old:.1f} MB/s at the {rung} rung — the "
                        f"async store lost to the synchronous "
                        f"discipline it replaced (check wal "
                        f"group_syncs amortization and the apply "
                        f"batch occupancy in the record's "
                        f"store_waterfall)"})

    # -- parity-delta RMW floor + routing collapse (ISSUE 20) ---------
    # ``fresh_rmw`` carries the rmw config's head-to-head (delta path
    # vs the SAME plugin forced full-stripe, per overwrite size,
    # measured in one process).  Three independent failure modes:
    # the delta path LOSING a size class to the full path it exists
    # to beat; the delta run silently riding the full path (an
    # eligibility/routing collapse makes the bench compare full vs
    # full and the floor check meaningless); and the forced-off
    # control still taking delta ops (the knob leaked, nothing was
    # controlled).  A fresh record beating history's best vs_baseline
    # is additionally held to ratio_tol like every throughput line.
    if fresh_rmw is not None:
        for label, row in sorted((fresh_rmw.get("sizes")
                                  or {}).items()):
            vf = row.get("vs_full") if isinstance(row, dict) else None
            if isinstance(vf, (int, float)) and vf < RMW_FLOOR:
                findings.append({
                    "check": "rmw-floor", "severity": "fail",
                    "message":
                        f"delta-path {label} overwrites at {vf:.3f}x "
                        f"the forced full-stripe run < {RMW_FLOOR:.2f}"
                        f" — the parity-delta path lost to the full "
                        f"re-encode it replaces (check the dirty "
                        f"census and delta_route_* verdicts in the "
                        f"record's delta block)"})
        dblock = fresh_rmw.get("delta") or {}
        dfrac = dblock.get("delta_fraction")
        if isinstance(dfrac, (int, float)) \
                and dfrac < RMW_MIN_DELTA_FRACTION:
            findings.append({
                "check": "rmw-delta-collapse", "severity": "fail",
                "message":
                    f"only {dfrac:.3f} of RMWs took the delta path "
                    f"(< {RMW_MIN_DELTA_FRACTION:.2f}) in the "
                    f"delta-enabled run — eligibility or routing "
                    f"collapsed ({dblock.get('fallbacks', 0)} "
                    f"fallbacks, census "
                    f"{dblock.get('dirty_census')}) and the bench "
                    f"compared full vs full"})
        ctrl = (fresh_rmw.get("full_run") or {}).get("rmw_ops")
        if isinstance(ctrl, (int, float)) and ctrl > 0:
            findings.append({
                "check": "rmw-control-leak", "severity": "fail",
                "message":
                    f"{int(ctrl)} delta op(s) fired in the "
                    f"osd_ec_delta_rmw=false control run — the knob "
                    f"does not gate the path and the comparison "
                    f"measured nothing"})
        rr = fresh_rmw.get("vs_baseline")
        best = None
        for rnd in history:
            rec = _pick(rnd["records"], _RMW_PREFIX)
            if rec and isinstance(rec.get("vs_baseline"),
                                  (int, float)):
                v = float(rec["vs_baseline"])
                best = v if best is None else max(best, v)
        if isinstance(rr, (int, float)) and best is not None \
                and rr < ratio_tol * best:
            findings.append({
                "check": "rmw-throughput-regression",
                "severity": "fail",
                "message":
                    f"delta-path 4 KiB overwrites at {rr:.3f}x the "
                    f"forced-full baseline < {ratio_tol:.2f} x best "
                    f"history {best:.3f}x"})
    return findings


def run(fresh_records: List[Dict], history: List[Dict],
        stage_tol: float = STAGE_TOL, ratio_tol: float = RATIO_TOL,
        min_device_fraction: float = MIN_DEVICE_FRACTION,
        hop_p99_factor: float = HOP_P99_FACTOR,
        overlap_tol: float = OVERLAP_TOL) -> int:
    att = _pick(fresh_records, _ATTRIB_PREFIX)
    cluster = _pick(fresh_records, _CLUSTER_PREFIX, _K8M4_MARK)
    headline = _pick(fresh_records, _HEADLINE_PREFIX)
    scaling = _pick(fresh_records, _SCALING_PREFIX)
    rebuild = _pick(fresh_records, _REBUILD_ATTRIB_PREFIX)
    mesh = _pick(fresh_records, _MESH_ATTRIB_PREFIX)
    load = _pick(fresh_records, _LOAD_PREFIX)
    selftune = _pick(fresh_records, _SELFTUNE_PREFIX)
    store_ladder = _pick(fresh_records, _STORE_LADDER_PREFIX)
    rmw = _pick(fresh_records, _RMW_PREFIX)
    ladder = None
    if scaling:
        cl_side = (scaling.get("classic") or {}).get("clients")
        cr_side = (scaling.get("crimson") or {}).get("clients")
        if cl_side and cr_side:
            ladder = {"classic": cl_side, "crimson": cr_side}
    if att is None and cluster is None:
        print("perf_trend: fresh input carries neither an "
              "attribution object nor a k8m4 cluster metric",
              file=sys.stderr)
        return 2
    findings = check(
        att, history,
        fresh_ratio=float(cluster["vs_baseline"])
        if cluster and isinstance(cluster.get("vs_baseline"),
                                  (int, float)) else None,
        fresh_headline_ratio=float(headline["vs_baseline"])
        if headline and isinstance(headline.get("vs_baseline"),
                                   (int, float)) else None,
        fresh_scaling=((scaling.get("crimson") or {}).get("clients")
                       if scaling else None),
        fresh_ladder=ladder, fresh_load=load,
        fresh_rebuild=rebuild, fresh_mesh=mesh,
        fresh_selftune=selftune,
        fresh_store_ladder=store_ladder,
        fresh_rmw=rmw,
        stage_tol=stage_tol, ratio_tol=ratio_tol,
        min_device_fraction=min_device_fraction,
        hop_p99_factor=hop_p99_factor, overlap_tol=overlap_tol)
    for f in findings:
        print(f"perf_trend {f['severity'].upper()} "
              f"[{f['check']}]: {f['message']}")
    if findings:
        return 1
    print("perf_trend ok: no regressions vs "
          f"{len(history)} history round(s)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fresh", required=True,
                    help="fresh run: attribution JSON object, "
                         "BENCH_r0N.json-style wrapper, or raw bench "
                         "stdout log")
    ap.add_argument("--history", nargs="*", default=None,
                    help="history files (default: BENCH_r0*.json "
                         "next to the repo root)")
    ap.add_argument("--stage-tol", type=float, default=STAGE_TOL)
    ap.add_argument("--ratio-tol", type=float, default=RATIO_TOL)
    ap.add_argument("--min-device-fraction", type=float,
                    default=MIN_DEVICE_FRACTION)
    ap.add_argument("--hop-p99-factor", type=float,
                    default=HOP_P99_FACTOR)
    ap.add_argument("--overlap-tol", type=float, default=OVERLAP_TOL)
    args = ap.parse_args(argv)
    paths = args.history if args.history else default_history_paths()
    if not paths:
        print("perf_trend: no history files", file=sys.stderr)
        return 2
    return run(load_fresh(args.fresh), load_history(paths),
               stage_tol=args.stage_tol, ratio_tol=args.ratio_tol,
               min_device_fraction=args.min_device_fraction,
               hop_p99_factor=args.hop_p99_factor,
               overlap_tol=args.overlap_tol)


if __name__ == "__main__":
    sys.exit(main())
