#!/usr/bin/env python3
"""Unified Chrome/Perfetto trace export across every daemon.

Merges the per-daemon ``dump_trace`` bundles (``ceph tell osd.N
dump_trace`` — recent hop ledgers by op class, optracker stage
timelines, flight-recorder events, per-shard reactor utilization
samples, sampler folded stacks) plus the client's objecter bundle
into ONE ``trace_event`` JSON loadable in ``ui.perfetto.dev`` (or
``chrome://tracing``) unmodified:

- one *process* per daemon (client, each OSD), named via ``M``
  metadata events;
- per-op tracks: every recent hop ledger becomes an enclosing op
  slice plus nested per-hop slices (``X`` complete events, charged to
  the hop that ends each interval — the same rule as
  ``utils/hops.charge``), lane-packed so concurrent ops never overlap
  on one thread track;
- optracker timelines: per-op stage slices between consecutive
  ``mark_event`` stamps;
- flight-recorder events as instants (``i``);
- per-shard reactor utilization + loop-lag counter tracks (``C``),
  which is the PR 8 "is multi-shard scaling real?" readout.

Hop ledgers use absolute wall-clock stamps, so slices from different
daemons line up on one timeline without clock translation.  All
timestamps are rebased to the earliest event and emitted in
microseconds (the trace_event contract).

Usage::

    ceph tell osd.0 dump_trace > osd0.json   # one bundle per daemon
    python tools/trace_export.py --out trace.json osd0.json osd1.json

``bench.py`` and the tier-1 structural test import
:func:`export_bundles` directly on live in-process bundles.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

try:
    from ceph_tpu.utils.hops import CHARGE_ORDER
    from ceph_tpu.utils.device_ledger import PHASE_ORDER
    from ceph_tpu.utils.store_ledger import (
        PHASE_ORDER as STORE_PHASE_ORDER)
except ImportError:                     # invoked as a script from tools/
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from ceph_tpu.utils.hops import CHARGE_ORDER
    from ceph_tpu.utils.device_ledger import PHASE_ORDER
    from ceph_tpu.utils.store_ledger import (
        PHASE_ORDER as STORE_PHASE_ORDER)

#: thread-id bases per track family (per daemon process); lanes for
#: concurrent ops fan out upward from the base
_TID_BASE = {"write": 100, "read": 200, "recovery": 300,
             "optracker": 400, "flight": 500, "reactor": 600,
             "device": 700, "tuner": 800, "store": 850}
_MAX_LANES = 64          # overlap-packing cap per track family
_DEVICE_LANE_STRIDE = 32  # tid span per JAX device id (mesh-ready)


def _as_dict(v) -> Dict:
    """Partial-bundle armor: a daemon that died mid-dump can leave
    any sub-block missing, null, or truncated to a non-dict; degrade
    to empty instead of KeyError/TypeError-ing the whole export."""
    return v if isinstance(v, dict) else {}


def _as_list(v) -> List:
    return v if isinstance(v, list) else []


class _Lanes:
    """Greedy interval packing: assign each op the first lane whose
    previous op already ended, so slices on one Perfetto thread track
    never overlap (overlapping X events render broken)."""

    def __init__(self) -> None:
        self._ends: List[float] = []

    def place(self, start: float, end: float) -> int:
        for i, e in enumerate(self._ends):
            if start >= e:
                self._ends[i] = end
                return i
        if len(self._ends) < _MAX_LANES:
            self._ends.append(end)
            return len(self._ends) - 1
        # saturated: reuse the lane that frees up first
        i = min(range(len(self._ends)), key=lambda j: self._ends[j])
        self._ends[i] = end
        return i


def _ledger_slices(ledger: Dict[str, float]):
    """-> (start, end, [(hop, t_start, t_end)]) in charge order, or
    None for degenerate ledgers."""
    stamps = [(name, ledger[name]) for name in CHARGE_ORDER
              if name in ledger]
    if len(stamps) < 2:
        return None
    spans = []
    prev_t = stamps[0][1]
    for name, t in stamps[1:]:
        if t >= prev_t:
            spans.append((name, prev_t, t))
            prev_t = t
    if not spans:
        return None
    return stamps[0][1], prev_t, spans


def _phase_slices(ledger: Dict[str, float], order):
    """-> (start, end, [(phase, t_start, t_end)]) in the given phase
    order (charge-to-ending-phase), or None for degenerate ledgers.
    Only the phase stamps are read — meta fields (op tags, byte
    counts, carved seconds) never look like timestamps here."""
    stamps = [(name, ledger[name]) for name in order
              if isinstance(ledger.get(name), (int, float))]
    if len(stamps) < 2:
        return None
    spans = []
    prev_t = stamps[0][1]
    for name, t in stamps[1:]:
        if t >= prev_t:
            spans.append((name, prev_t, t))
            prev_t = t
    if not spans:
        return None
    return stamps[0][1], prev_t, spans


def export_bundles(bundles: List[Dict]) -> Dict:
    """Merge daemon bundles -> Chrome trace_event JSON dict."""
    events: List[Dict] = []
    other: Dict[str, object] = {}
    # pass 1: find the rebase origin across every timestamped source
    t0: Optional[float] = None

    def _see(ts: Optional[float]) -> None:
        nonlocal t0
        if isinstance(ts, (int, float)) and ts > 0:
            t0 = ts if t0 is None else min(t0, ts)

    for b in bundles:
        b = _as_dict(b)
        for ledgers in _as_dict(b.get("ledgers")).values():
            for led in _as_list(ledgers):
                for ts in _as_dict(led).values():
                    _see(ts)
        for op in _as_list(b.get("ops")):
            _see(_as_dict(op).get("initiated_at"))
        for ev in _as_list(_as_dict(b.get("flight")).get("events")):
            _see(_as_dict(ev).get("time"))
        for r in _as_list(b.get("reactors")):
            for s in _as_list(_as_dict(r).get("util")):
                _see(_as_dict(s).get("ts"))
        for led in _as_list(_as_dict(b.get("device")).get("ledgers")):
            led = _as_dict(led)
            # phase stamps only: device ledgers carry meta fields
            # (device id, payload bytes) that are NOT timestamps
            for name in PHASE_ORDER:
                _see(led.get(name))
        for led in _as_list(_as_dict(b.get("store")).get("ledgers")):
            led = _as_dict(led)
            for name in STORE_PHASE_ORDER:
                _see(led.get(name))
    if t0 is None:
        t0 = 0.0

    def us(ts: float) -> float:
        return round((ts - t0) * 1e6, 1)

    for pid, b in enumerate(bundles, start=1):
        b = _as_dict(b)
        daemon = b.get("daemon") or f"daemon.{pid}"
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": daemon}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": 0,
                       "args": {"sort_index": pid}})
        named_tids: Dict[int, str] = {}

        # -- per-op hop-ledger tracks ------------------------------
        for cls, ledgers in sorted(_as_dict(b.get("ledgers")).items()):
            base = _TID_BASE.get(cls, 900)
            lanes = _Lanes()
            for led in _as_list(ledgers):
                if not isinstance(led, dict):
                    continue
                sl = _ledger_slices(led)
                if sl is None:
                    continue
                start, end, spans = sl
                tid = base + lanes.place(start, end)
                named_tids.setdefault(tid, f"{cls} ops")
                events.append({"ph": "X", "name": f"{cls}_op",
                               "cat": cls, "pid": pid, "tid": tid,
                               "ts": us(start),
                               "dur": round((end - start) * 1e6, 1)})
                for hop, hs, he in spans:
                    events.append({
                        "ph": "X", "name": hop, "cat": cls,
                        "pid": pid, "tid": tid, "ts": us(hs),
                        "dur": round((he - hs) * 1e6, 1)})

        # -- optracker stage timelines -----------------------------
        lanes = _Lanes()
        base = _TID_BASE["optracker"]
        for op in _as_list(b.get("ops")):
            op = _as_dict(op)
            evs = [(e.get("time"), e.get("event"))
                   for e in _as_list(op.get("events"))
                   if isinstance(e, dict)
                   and isinstance(e.get("time"), (int, float))]
            if len(evs) < 2:
                continue
            evs.sort(key=lambda te: te[0])
            start, end = evs[0][0], evs[-1][0]
            tid = base + lanes.place(start, end)
            named_tids.setdefault(tid, "optracker")
            events.append({"ph": "X", "name":
                           (op.get("description") or "op")[:80],
                           "cat": "optracker", "pid": pid, "tid": tid,
                           "ts": us(start),
                           "dur": round((end - start) * 1e6, 1)})
            prev_t = evs[0][0]
            for t, name in evs[1:]:
                if t > prev_t:
                    events.append({
                        "ph": "X", "name": str(name),
                        "cat": "optracker", "pid": pid, "tid": tid,
                        "ts": us(prev_t),
                        "dur": round((t - prev_t) * 1e6, 1)})
                prev_t = t

        # -- flight-recorder instants ------------------------------
        # tune_step events (ISSUE 15: every autotuner decision is
        # flight-recorded) get their own named lane so knob walks
        # read as a timeline instead of drowning in route verdicts
        tid = _TID_BASE["flight"]
        tuner_tid = _TID_BASE["tuner"]
        fl = [e for e in
              _as_list(_as_dict(b.get("flight")).get("events"))
              if isinstance(e, dict)]
        if fl:
            named_tids.setdefault(tid, "flight recorder")
        for ev in fl:
            ts = ev.get("time")
            if not isinstance(ts, (int, float)):
                continue
            args = {k: v for k, v in ev.items()
                    if k not in ("time", "mono")}
            kind = str(ev.get("kind", "ev"))
            if kind == "tune_step":
                named_tids.setdefault(tuner_tid, "tuner decisions")
                name = kind
                knob, verdict = ev.get("knob"), ev.get("verdict")
                if knob and verdict:
                    name = f"{verdict}:{knob}"
                events.append({"ph": "i", "name": name,
                               "cat": "tuner", "pid": pid,
                               "tid": tuner_tid, "ts": us(ts),
                               "s": "p", "args": args})
                continue
            events.append({"ph": "i", "name": kind,
                           "cat": "flight", "pid": pid, "tid": tid,
                           "ts": us(ts), "s": "p", "args": args})

        # -- per-shard reactor utilization counters ----------------
        for r in _as_list(b.get("reactors")):
            r = _as_dict(r)
            shard = r.get("shard", 0)
            for s in _as_list(r.get("util")):
                if not isinstance(s, dict):
                    continue
                ts = s.get("ts")
                if not isinstance(ts, (int, float)):
                    continue
                events.append({
                    "ph": "C", "name": f"reactor{shard}_util",
                    "pid": pid, "tid": 0, "ts": us(ts),
                    "args": {"util": round(s.get("util", 0.0), 4)}})
                events.append({
                    "ph": "C", "name": f"reactor{shard}_loop_lag_ms",
                    "pid": pid, "tid": 0, "ts": us(ts),
                    "args": {"lag": round(
                        s.get("loop_lag_s", 0.0) * 1e3, 3)}})

        # -- per-device phase lanes + pipeline counters ------------
        # every recent device-group ledger becomes an enclosing
        # {encode,decode}_group slice plus nested per-phase slices
        # (charge-to-ending-phase, same rule as the hop tracks), one
        # tid band per JAX device id so a mesh shows one lane set per
        # chip.  Two derived counter tracks per device: groups in
        # flight (staging occupancy) and the fraction of each h2d
        # hidden under the previous group's compute (pipeline
        # overlap — the PR 5 double-buffering readout).
        dev_block = _as_dict(b.get("device"))
        by_dev: Dict[int, List] = {}
        for led in _as_list(dev_block.get("ledgers")):
            if not isinstance(led, dict):
                continue
            sl = _phase_slices(led, PHASE_ORDER)
            if sl is None:
                continue
            try:
                dev = int(led.get("device", 0) or 0)
            except (TypeError, ValueError):
                dev = 0
            by_dev.setdefault(dev, []).append((led, sl))
        for dev, items in sorted(by_dev.items()):
            # device -1 is the host lane (CPU-twin groups): it sits
            # one stride below the device band and gets its own name
            base = _TID_BASE["device"] + dev * _DEVICE_LANE_STRIDE
            label = f"device{dev}" if dev >= 0 else "cpu_twin"
            lanes = _Lanes()
            items.sort(key=lambda it: it[1][0])
            occ_edges: List = []
            for led, (start, end, spans) in items:
                tid = base + min(lanes.place(start, end),
                                 _DEVICE_LANE_STRIDE - 1)
                named_tids.setdefault(
                    tid, f"device{dev} phases" if dev >= 0
                    else "cpu-twin phases")
                gname = str(led.get("group", "encode")) + "_group"
                events.append({
                    "ph": "X", "name": gname, "cat": "device",
                    "pid": pid, "tid": tid, "ts": us(start),
                    "dur": round((end - start) * 1e6, 1),
                    "args": {"device": dev,
                             "bytes": led.get("bytes", 0)}})
                for phase, hs, he in spans:
                    events.append({
                        "ph": "X", "name": phase, "cat": "device",
                        "pid": pid, "tid": tid, "ts": us(hs),
                        "dur": round((he - hs) * 1e6, 1)})
                occ_edges.append((start, 1))
                occ_edges.append((end, -1))
            occ_edges.sort()
            running = 0
            for ets, delta in occ_edges:
                running += delta
                events.append({
                    "ph": "C",
                    "name": f"{label}_groups_in_flight",
                    "pid": pid, "tid": 0, "ts": us(ets),
                    "args": {"groups": running}})
            prev = None
            for led, _sl in items:
                if prev is not None:
                    try:
                        ov = max(0.0,
                                 min(led["h2d_done"],
                                     prev["compute_done"])
                                 - max(led["h2d_start"],
                                       prev["compute_start"]))
                        h2d = max(1e-9,
                                  led["h2d_done"] - led["h2d_start"])
                        events.append({
                            "ph": "C",
                            "name": f"{label}_overlap_frac",
                            "pid": pid, "tid": 0,
                            "ts": us(led["h2d_start"]),
                            "args": {"frac": round(
                                min(1.0, ov / h2d), 4)}})
                    except (KeyError, TypeError):
                        pass
                prev = led
        # -- store transaction phase lanes (ISSUE 16) --------------
        # every recent store-transaction ledger becomes an enclosing
        # store_txn slice plus nested per-phase slices (journal
        # append/fsync, alloc, data write, compress, kv commit —
        # charge-to-ending-phase, same rule as the hop and device
        # tracks).  Store ledgers use the same absolute clock as the
        # hop ledgers, so these slices land NESTED under the
        # store_apply hop slice of the enclosing op on the timeline.
        base = _TID_BASE["store"]
        lanes = _Lanes()
        store_leds = [led for led in
                      _as_list(_as_dict(b.get("store")).get("ledgers"))
                      if isinstance(led, dict)]
        store_items = []
        for led in store_leds:
            sl = _phase_slices(led, STORE_PHASE_ORDER)
            if sl is not None:
                store_items.append((led, sl))
        store_items.sort(key=lambda it: it[1][0])
        for led, (start, end, spans) in store_items:
            tid = base + lanes.place(start, end)
            named_tids.setdefault(tid, "store txns")
            args = {"txns": led.get("txns", 1)}
            if led.get("op"):
                args["op"] = led["op"]
            if led.get("bytes_written"):
                args["bytes"] = led["bytes_written"]
            events.append({
                "ph": "X", "name": "store_txn", "cat": "store",
                "pid": pid, "tid": tid, "ts": us(start),
                "dur": round((end - start) * 1e6, 1),
                "args": args})
            for phase, hs, he in spans:
                events.append({
                    "ph": "X", "name": phase, "cat": "store",
                    "pid": pid, "tid": tid, "ts": us(hs),
                    "dur": round((he - hs) * 1e6, 1)})

        mem = _as_dict(dev_block.get("memory"))
        if mem and by_dev:
            last_ts = max(end for items in by_dev.values()
                          for _, (start, end, spans) in items)
            events.append({
                "ph": "C", "name": "staging_host_bytes",
                "pid": pid, "tid": 0, "ts": us(last_ts),
                "args": {"bytes": mem.get("staging_host_bytes", 0),
                         "peak": mem.get("staging_host_bytes_peak",
                                         0)}})

        for tid, name in sorted(named_tids.items()):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": name}})
            events.append({"ph": "M", "name": "thread_sort_index",
                           "pid": pid, "tid": tid,
                           "args": {"sort_index": tid}})

        folded = b.get("folded")
        if folded:
            other[f"{daemon}_folded"] = folded

    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def write_trace(bundles: List[Dict], path: str) -> Dict:
    trace = export_bundles(bundles)
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundles", nargs="+",
                    help="per-daemon dump_trace JSON files")
    ap.add_argument("--out", default="trace.json",
                    help="output trace_event JSON path")
    args = ap.parse_args(argv)
    bundles = []
    for p in args.bundles:
        try:
            with open(p) as f:
                bundles.append(json.load(f))
        except (OSError, ValueError) as e:
            print(f"trace_export: unreadable bundle {p}: {e}",
                  file=sys.stderr)
            return 2
    trace = write_trace(bundles, args.out)
    n_procs = len({e["pid"] for e in trace["traceEvents"]})
    print(f"trace_export: {len(trace['traceEvents'])} events across "
          f"{n_procs} process(es) -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
