#!/usr/bin/env python3
"""Unified Chrome/Perfetto trace export across every daemon.

Merges the per-daemon ``dump_trace`` bundles (``ceph tell osd.N
dump_trace`` — recent hop ledgers by op class, optracker stage
timelines, flight-recorder events, per-shard reactor utilization
samples, sampler folded stacks) plus the client's objecter bundle
into ONE ``trace_event`` JSON loadable in ``ui.perfetto.dev`` (or
``chrome://tracing``) unmodified:

- one *process* per daemon (client, each OSD), named via ``M``
  metadata events;
- per-op tracks: every recent hop ledger becomes an enclosing op
  slice plus nested per-hop slices (``X`` complete events, charged to
  the hop that ends each interval — the same rule as
  ``utils/hops.charge``), lane-packed so concurrent ops never overlap
  on one thread track;
- optracker timelines: per-op stage slices between consecutive
  ``mark_event`` stamps;
- flight-recorder events as instants (``i``);
- per-shard reactor utilization + loop-lag counter tracks (``C``),
  which is the PR 8 "is multi-shard scaling real?" readout.

Hop ledgers use absolute wall-clock stamps, so slices from different
daemons line up on one timeline without clock translation.  All
timestamps are rebased to the earliest event and emitted in
microseconds (the trace_event contract).

Usage::

    ceph tell osd.0 dump_trace > osd0.json   # one bundle per daemon
    python tools/trace_export.py --out trace.json osd0.json osd1.json

``bench.py`` and the tier-1 structural test import
:func:`export_bundles` directly on live in-process bundles.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

try:
    from ceph_tpu.utils.hops import CHARGE_ORDER
except ImportError:                     # invoked as a script from tools/
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from ceph_tpu.utils.hops import CHARGE_ORDER

#: thread-id bases per track family (per daemon process); lanes for
#: concurrent ops fan out upward from the base
_TID_BASE = {"write": 100, "read": 200, "recovery": 300,
             "optracker": 400, "flight": 500, "reactor": 600}
_MAX_LANES = 64          # overlap-packing cap per track family


class _Lanes:
    """Greedy interval packing: assign each op the first lane whose
    previous op already ended, so slices on one Perfetto thread track
    never overlap (overlapping X events render broken)."""

    def __init__(self) -> None:
        self._ends: List[float] = []

    def place(self, start: float, end: float) -> int:
        for i, e in enumerate(self._ends):
            if start >= e:
                self._ends[i] = end
                return i
        if len(self._ends) < _MAX_LANES:
            self._ends.append(end)
            return len(self._ends) - 1
        # saturated: reuse the lane that frees up first
        i = min(range(len(self._ends)), key=lambda j: self._ends[j])
        self._ends[i] = end
        return i


def _ledger_slices(ledger: Dict[str, float]):
    """-> (start, end, [(hop, t_start, t_end)]) in charge order, or
    None for degenerate ledgers."""
    stamps = [(name, ledger[name]) for name in CHARGE_ORDER
              if name in ledger]
    if len(stamps) < 2:
        return None
    spans = []
    prev_t = stamps[0][1]
    for name, t in stamps[1:]:
        if t >= prev_t:
            spans.append((name, prev_t, t))
            prev_t = t
    if not spans:
        return None
    return stamps[0][1], prev_t, spans


def export_bundles(bundles: List[Dict]) -> Dict:
    """Merge daemon bundles -> Chrome trace_event JSON dict."""
    events: List[Dict] = []
    other: Dict[str, object] = {}
    # pass 1: find the rebase origin across every timestamped source
    t0: Optional[float] = None

    def _see(ts: Optional[float]) -> None:
        nonlocal t0
        if isinstance(ts, (int, float)) and ts > 0:
            t0 = ts if t0 is None else min(t0, ts)

    for b in bundles:
        for ledgers in (b.get("ledgers") or {}).values():
            for led in ledgers or []:
                for ts in led.values():
                    _see(ts)
        for op in b.get("ops") or []:
            _see(op.get("initiated_at"))
        for ev in (b.get("flight") or {}).get("events") or []:
            _see(ev.get("time"))
        for r in b.get("reactors") or []:
            for s in r.get("util") or []:
                _see(s.get("ts"))
    if t0 is None:
        t0 = 0.0

    def us(ts: float) -> float:
        return round((ts - t0) * 1e6, 1)

    for pid, b in enumerate(bundles, start=1):
        daemon = b.get("daemon", f"daemon.{pid}")
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": daemon}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": 0,
                       "args": {"sort_index": pid}})
        named_tids: Dict[int, str] = {}

        # -- per-op hop-ledger tracks ------------------------------
        for cls, ledgers in sorted((b.get("ledgers") or {}).items()):
            base = _TID_BASE.get(cls, 900)
            lanes = _Lanes()
            for led in ledgers or []:
                sl = _ledger_slices(led)
                if sl is None:
                    continue
                start, end, spans = sl
                tid = base + lanes.place(start, end)
                named_tids.setdefault(tid, f"{cls} ops")
                events.append({"ph": "X", "name": f"{cls}_op",
                               "cat": cls, "pid": pid, "tid": tid,
                               "ts": us(start),
                               "dur": round((end - start) * 1e6, 1)})
                for hop, hs, he in spans:
                    events.append({
                        "ph": "X", "name": hop, "cat": cls,
                        "pid": pid, "tid": tid, "ts": us(hs),
                        "dur": round((he - hs) * 1e6, 1)})

        # -- optracker stage timelines -----------------------------
        lanes = _Lanes()
        base = _TID_BASE["optracker"]
        for op in b.get("ops") or []:
            evs = [(e.get("time"), e.get("event"))
                   for e in op.get("events") or []
                   if isinstance(e.get("time"), (int, float))]
            if len(evs) < 2:
                continue
            evs.sort(key=lambda te: te[0])
            start, end = evs[0][0], evs[-1][0]
            tid = base + lanes.place(start, end)
            named_tids.setdefault(tid, "optracker")
            events.append({"ph": "X", "name":
                           (op.get("description") or "op")[:80],
                           "cat": "optracker", "pid": pid, "tid": tid,
                           "ts": us(start),
                           "dur": round((end - start) * 1e6, 1)})
            prev_t = evs[0][0]
            for t, name in evs[1:]:
                if t > prev_t:
                    events.append({
                        "ph": "X", "name": str(name),
                        "cat": "optracker", "pid": pid, "tid": tid,
                        "ts": us(prev_t),
                        "dur": round((t - prev_t) * 1e6, 1)})
                prev_t = t

        # -- flight-recorder instants ------------------------------
        tid = _TID_BASE["flight"]
        fl = (b.get("flight") or {}).get("events") or []
        if fl:
            named_tids.setdefault(tid, "flight recorder")
        for ev in fl:
            ts = ev.get("time")
            if not isinstance(ts, (int, float)):
                continue
            args = {k: v for k, v in ev.items()
                    if k not in ("time", "mono")}
            events.append({"ph": "i", "name": str(ev.get("kind", "ev")),
                           "cat": "flight", "pid": pid, "tid": tid,
                           "ts": us(ts), "s": "p", "args": args})

        # -- per-shard reactor utilization counters ----------------
        for r in b.get("reactors") or []:
            shard = r.get("shard", 0)
            for s in r.get("util") or []:
                ts = s.get("ts")
                if not isinstance(ts, (int, float)):
                    continue
                events.append({
                    "ph": "C", "name": f"reactor{shard}_util",
                    "pid": pid, "tid": 0, "ts": us(ts),
                    "args": {"util": round(s.get("util", 0.0), 4)}})
                events.append({
                    "ph": "C", "name": f"reactor{shard}_loop_lag_ms",
                    "pid": pid, "tid": 0, "ts": us(ts),
                    "args": {"lag": round(
                        s.get("loop_lag_s", 0.0) * 1e3, 3)}})

        for tid, name in sorted(named_tids.items()):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": name}})
            events.append({"ph": "M", "name": "thread_sort_index",
                           "pid": pid, "tid": tid,
                           "args": {"sort_index": tid}})

        folded = b.get("folded")
        if folded:
            other[f"{daemon}_folded"] = folded

    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def write_trace(bundles: List[Dict], path: str) -> Dict:
    trace = export_bundles(bundles)
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundles", nargs="+",
                    help="per-daemon dump_trace JSON files")
    ap.add_argument("--out", default="trace.json",
                    help="output trace_event JSON path")
    args = ap.parse_args(argv)
    bundles = []
    for p in args.bundles:
        try:
            with open(p) as f:
                bundles.append(json.load(f))
        except (OSError, ValueError) as e:
            print(f"trace_export: unreadable bundle {p}: {e}",
                  file=sys.stderr)
            return 2
    trace = write_trace(bundles, args.out)
    n_procs = len({e["pid"] for e in trace["traceEvents"]})
    print(f"trace_export: {len(trace['traceEvents'])} events across "
          f"{n_procs} process(es) -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
